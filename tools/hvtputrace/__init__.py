"""hvtputrace: merge per-rank hvtpu trace files and attribute stragglers.

Input: a trace directory produced by ``HVTPU_TRACE=<dir>`` (or
``hvtpurun --trace-dir``) holding one ``rank<N>.trace.json`` Chrome
trace per rank, each carrying two metadata instants written by
``horovod_tpu/obs/tracing.py``:

  * ``clock_anchor``  — ``wall_t0_us``: the local wall clock at the
    file's ``ts=0`` instant
  * ``clock_offset``  — ``offset_us``: rank0-relative clock offset
    (add it to a local wall timestamp to get rank-0 time), with its
    ``error_bound_us`` from the min-RTT NTP-style KV handshake

``merge`` rebases every rank's relative timestamps onto rank 0's
clock — ``ts_rank0 = wall_t0_us + ts + offset_us − epoch`` — and emits
one Perfetto/chrome://tracing-loadable JSON array with one process
lane per rank.

``report`` correlates spans across ranks by their rank-agnostic
``trace_id`` (``tensor#occurrence``, agreed by the negotiation
protocol / SPMD program order) and computes per-collective arrival
skew (who started last, by how much), a per-rank wait-vs-compute
decomposition, and a top-N straggler table.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

_RANK_FILE_RE = re.compile(r"rank(\d+)\.trace\.json$")

# Span phases that are communication/coordination wait from the
# submitting rank's perspective (everything else in the trace extent
# is treated as compute for the wait-vs-compute split).
_WAIT_PHASES = {"NEGOTIATE", "QUEUE", "FUSE", "EXEC", "PREDICT"}

# Input-pipeline wait (data/loader.py DATA_WAIT spans): bucketed
# separately so the per-rank decomposition reads input vs compute vs
# comms — a rank stalled on its host data source attributes to input,
# not to the collective it subsequently holds up.
_DATA_PHASES = {"DATA_WAIT"}


def _load_events(path: str) -> List[dict]:
    """Parse one per-rank trace, tolerating a truncated file (process
    died before Timeline.close wrote the closing bracket, possibly
    mid-event).  The writer emits one event per line, so repair drops
    trailing lines until the remainder closes as a valid array."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = None
        while text:
            repaired = text.rstrip().rstrip(",")
            if not repaired.endswith("]"):
                repaired += "\n]"
            try:
                data = json.loads(repaired)
                break
            except json.JSONDecodeError:
                cut = text.rstrip().rfind("\n")
                if cut <= 0:
                    raise
                text = text[:cut]
    return [e for e in data if isinstance(e, dict)]


def load_rank_traces(trace_dir: str) -> Dict[int, List[dict]]:
    """rank -> event list for every rank<N>.trace.json in the dir."""
    out: Dict[int, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "rank*.trace.json"))):
        m = _RANK_FILE_RE.search(os.path.basename(path))
        if not m:
            continue
        out[int(m.group(1))] = _load_events(path)
    if not out:
        raise FileNotFoundError(
            f"no rank*.trace.json files in {trace_dir!r} — was the job "
            "run with HVTPU_TRACE/--trace-dir?")
    return out


def _find_instant(events: List[dict], name: str) -> Optional[dict]:
    for e in events:
        if e.get("name") == name and e.get("ph") == "i":
            return e.get("args", {})
    return None


def clock_metadata(events: List[dict]) -> Tuple[Optional[float],
                                                Optional[float],
                                                Optional[float]]:
    """(wall_t0_us, offset_us, error_bound_us) for one rank's trace.
    offset_us is None when the KV handshake degraded on that rank."""
    anchor = _find_instant(events, "clock_anchor") or {}
    off = _find_instant(events, "clock_offset") or {}
    return (anchor.get("wall_t0_us"), off.get("offset_us"),
            off.get("error_bound_us"))


def merge(trace_dir: str) -> List[dict]:
    """Fuse per-rank traces into one event list on rank 0's clock.

    Ranks whose clock_offset degraded to None merge with offset 0 (their
    lane stays internally consistent but may sit skewed against the
    others); ranks missing the wall anchor keep raw timestamps.
    """
    traces = load_rank_traces(trace_dir)
    rebased: List[Tuple[int, dict]] = []
    epochs: List[float] = []
    per_rank_base: Dict[int, Optional[float]] = {}
    for rank, events in traces.items():
        wall_t0_us, offset_us, _err = clock_metadata(events)
        if wall_t0_us is None:
            per_rank_base[rank] = None
            continue
        base = float(wall_t0_us) + float(offset_us or 0.0)
        per_rank_base[rank] = base
        epochs.append(base)
    epoch = min(epochs) if epochs else 0.0
    merged: List[dict] = []
    for rank, events in traces.items():
        base = per_rank_base[rank]
        shift = 0.0 if base is None else base - epoch
        for e in events:
            e = dict(e)
            e["pid"] = rank
            if "ts" in e:
                e["ts"] = float(e["ts"]) + shift
            merged.append(e)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return merged


# ---------------------------------------------------------------------------
# attribution analysis
# ---------------------------------------------------------------------------

def _collect_spans(merged: List[dict]) -> Dict[Tuple[str, int], List[dict]]:
    """(trace_id, rank) -> completed [{phase, t0, t1}] span list, built
    by pairing B/E events per (rank, tid) track."""
    open_by_track: Dict[Tuple[int, int], dict] = {}
    spans: Dict[Tuple[str, int], List[dict]] = {}
    for e in merged:
        ph = e.get("ph")
        if ph not in ("B", "E"):
            continue
        track = (e.get("pid", 0), e.get("tid", 0))
        if ph == "B":
            tid = (e.get("args") or {}).get("trace_id")
            if tid is None:
                continue
            open_by_track[track] = {
                "trace_id": tid,
                "tensor": (e.get("args") or {}).get("tensor"),
                "phase": e.get("name"),
                "t0": float(e.get("ts", 0.0)),
            }
        else:
            sp = open_by_track.pop(track, None)
            if sp is None:
                continue
            sp["t1"] = float(e.get("ts", 0.0))
            spans.setdefault((sp["trace_id"], track[0]), []).append(sp)
    return spans


def report(trace_dir: str, top: int = 10) -> dict:
    """Straggler-attribution analysis over a trace directory."""
    merged = merge(trace_dir)
    traces = load_rank_traces(trace_dir)
    spans = _collect_spans(merged)

    # per-collective arrival skew: first span start per (trace_id, rank)
    arrivals: Dict[str, Dict[int, float]] = {}
    for (tid, rank), sps in spans.items():
        arrivals.setdefault(tid, {})[rank] = min(s["t0"] for s in sps)
    collectives = []
    last_count: Dict[int, int] = {}
    skew_sum: Dict[int, float] = {}
    for tid, by_rank in sorted(arrivals.items()):
        if len(by_rank) < 2:
            continue
        last_rank = max(by_rank, key=by_rank.get)
        first_rank = min(by_rank, key=by_rank.get)
        skew_us = by_rank[last_rank] - by_rank[first_rank]
        last_count[last_rank] = last_count.get(last_rank, 0) + 1
        skew_sum[last_rank] = skew_sum.get(last_rank, 0.0) + skew_us
        collectives.append({
            "trace_id": tid,
            "ranks": sorted(by_rank),
            "first_rank": first_rank,
            "last_rank": last_rank,
            "arrival_skew_us": round(skew_us, 1),
        })

    # per-rank wait vs input vs compute: wait = time inside
    # coordination/comm span phases, data_wait = time inside the input
    # pipeline's DATA_WAIT spans; compute = rest of the trace extent
    per_rank: Dict[int, dict] = {}
    for rank in traces:
        ts = [float(e["ts"]) for e in merged
              if e.get("pid") == rank and "ts" in e]
        extent = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        wait = sum(
            s["t1"] - s["t0"]
            for (tid, r), sps in spans.items() if r == rank
            for s in sps if s["phase"] in _WAIT_PHASES)
        data_wait = sum(
            s["t1"] - s["t0"]
            for (tid, r), sps in spans.items() if r == rank
            for s in sps if s["phase"] in _DATA_PHASES)
        wall_t0, offset, err = clock_metadata(traces[rank])
        per_rank[rank] = {
            "trace_extent_us": round(extent, 1),
            "wait_us": round(wait, 1),
            "data_wait_us": round(data_wait, 1),
            "compute_us": round(max(extent - wait - data_wait, 0.0), 1),
            "wait_fraction": round(wait / extent, 4) if extent else 0.0,
            "data_wait_fraction":
                round(data_wait / extent, 4) if extent else 0.0,
            "clock_offset_us": offset,
            "clock_error_bound_us": err,
        }

    stragglers = sorted(
        ({"rank": r, "times_last": n,
          "total_skew_us": round(skew_sum.get(r, 0.0), 1)}
         for r, n in last_count.items()),
        key=lambda row: (-row["times_last"], -row["total_skew_us"]),
    )[:top]
    return {
        "trace_dir": trace_dir,
        "ranks": sorted(traces),
        "collectives": collectives,
        "per_rank": per_rank,
        "stragglers": stragglers,
    }


# ---------------------------------------------------------------------------
# overlap analysis: six-way step decomposition (joined against an XLA
# device profile when one is available)
# ---------------------------------------------------------------------------
# Interval helpers mirror horovod_tpu/obs/stepprof.py (the runtime
# side); duplicated here so the offline tool needs no jax — equality of
# the two decompositions is pinned by tests/test_stepprof.py.

_HOST_PHASES = {"NEGOTIATE", "QUEUE", "FUSE", "PREDICT"}
_COMM_PHASES = {"EXEC"}


def _iv_union(ivs):
    out = []
    for t0, t1 in sorted((a, b) for a, b in ivs if b > a):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _iv_intersect(a, b):
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        t0, t1 = max(a[i][0], b[j][0]), min(a[i][1], b[j][1])
        if t1 > t0:
            out.append((t0, t1))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _iv_subtract(a, b):
    out = []
    for t0, t1 in a:
        cur = t0
        for b0, b1 in b:
            if b1 <= cur or b0 >= t1:
                continue
            if b0 > cur:
                out.append((cur, b0))
            cur = max(cur, b1)
        if cur < t1:
            out.append((cur, t1))
    return out


def _iv_total(ivs):
    return sum(t1 - t0 for t0, t1 in ivs)


def decompose_window(t0, t1, *, compute=(), comm=(), data=(), host=()):
    """Six-way split of [t0, t1); same priority order and invariant
    (parts sum to the wall) as stepprof.decompose."""
    window = [(t0, t1)]
    comp_u = _iv_intersect(_iv_union(compute), window)
    comm_u = _iv_intersect(_iv_union(comm), window)
    overlapped = _iv_intersect(comp_u, comm_u)
    busy = _iv_union(list(comp_u) + list(comm_u))
    data_w = _iv_subtract(_iv_intersect(_iv_union(data), window), busy)
    host_w = _iv_subtract(
        _iv_intersect(_iv_union(host), window),
        _iv_union(list(busy) + list(data_w)))
    parts = {
        "compute": _iv_total(_iv_subtract(comp_u, comm_u)),
        "overlapped_comm": _iv_total(overlapped),
        "exposed_comm": _iv_total(_iv_subtract(comm_u, comp_u)),
        "data_wait": _iv_total(data_w),
        "host": _iv_total(host_w),
    }
    parts["idle"] = max((t1 - t0) - sum(parts.values()), 0.0)
    parts["step_wall"] = t1 - t0
    return parts


def _load_xplane_parser():
    """Standalone-load horovod_tpu/obs/profile.py (it is stdlib-only;
    importing it through the horovod_tpu package would pull in jax,
    which this offline tool must not require)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "horovod_tpu", "obs", "profile.py")
    spec = importlib.util.spec_from_file_location(
        "_hvtputrace_xplane", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# Device timestamps joined on the wall clock when plausible; anything
# else (relative clocks, fixtures) is re-anchored onto the rank's
# first comm span.
_CLOCK_SANITY_US = 3600e6


def _device_intervals(xplane_dir, ranks):
    """rank -> (compute_ivs, comm_ivs) in device wall µs, plus the
    load_profile status dict.  Sorted device planes map onto sorted
    ranks by index; ranks beyond the plane count degrade to host-only
    attribution."""
    prof = _load_xplane_parser().load_profile(xplane_dir)
    per_rank = {}
    if prof["status"] == "ok":
        planes = sorted(prof["planes"])
        for i, rank in enumerate(sorted(ranks)):
            if i >= len(planes):
                break
            comp, comm = [], []
            for iv in prof["planes"][planes[i]]:
                (comm if iv["comm"] else comp).append(
                    (iv["t0_us"], iv["t1_us"]))
            per_rank[rank] = (comp, comm)
    return per_rank, {"status": prof["status"],
                      "reason": prof.get("reason", ""),
                      "path": prof.get("path")}


def overlap(trace_dir: str, xplane_dir: Optional[str] = None,
            top: int = 10) -> dict:
    """Measured compute/communication overlap decomposition.

    Joins the merged rank traces (EXEC comm spans, DATA_WAIT spans,
    NEGOTIATE/QUEUE/FUSE/PREDICT coordination spans, step_boundary
    instants) with an optional XLA device profile.  With a device
    profile, compute and comm come off the device timeline and EXEC
    span remainders attribute to host; without one the tool degrades
    gracefully: EXEC spans are comm (all of it exposed — the host
    cannot observe overlap), and non-span time is inferred compute.

    Every rank's six parts sum to its step-window wall time.
    """
    merged = merge(trace_dir)
    traces = load_rank_traces(trace_dir)
    spans = _collect_spans(merged)
    ranks = sorted(traces)

    dev, xplane_info = (_device_intervals(xplane_dir, ranks)
                        if xplane_dir else
                        ({}, {"status": "no-profile", "path": None,
                              "reason": "no --xplane directory given"}))

    # Re-derive each rank's merge shift (wall µs -> merged timeline)
    # the same way merge() does, so device wall timestamps and DONE
    # wall annotations can be placed on the merged clock.
    bases = {}
    for rank, events in traces.items():
        wall_t0_us, offset_us, _err = clock_metadata(events)
        bases[rank] = (None if wall_t0_us is None
                       else float(wall_t0_us) + float(offset_us or 0.0))
    known = [b for b in bases.values() if b is not None]
    epoch = min(known) if known else 0.0

    per_rank = {}
    exposed_rows = []
    for rank in ranks:
        events = [e for e in merged if e.get("pid") == rank]
        ts = [float(e["ts"]) for e in events if "ts" in e]
        extent = (min(ts), max(ts)) if len(ts) > 1 else (0.0, 0.0)
        bounds = sorted(float(e["ts"]) for e in events
                        if e.get("ph") == "i"
                        and e.get("name") == "step_boundary")
        windows = (list(zip(bounds, bounds[1:])) if len(bounds) >= 2
                   else ([extent] if extent[1] > extent[0] else []))

        comm_sp, host_iv, data_iv = [], [], []
        for (tid, r), sps in spans.items():
            if r != rank:
                continue
            for s in sps:
                if s["phase"] in _COMM_PHASES:
                    comm_sp.append((s["t0"], s["t1"], tid, s["tensor"]))
                elif s["phase"] in _HOST_PHASES:
                    host_iv.append((s["t0"], s["t1"]))
                elif s["phase"] == "DATA_WAIT":
                    data_iv.append((s["t0"], s["t1"]))
        comm_iv = [(t0, t1) for t0, t1, _tid, _tn in comm_sp]

        mode = "host-only"
        comp_u = []
        if rank in dev:
            dev_comp, dev_comm = dev[rank]
            # device wall µs -> merged timeline: merged ts = wall +
            # offset − epoch (what merge() applies to span
            # timestamps, whose wall_t0 anchor cancels); fixtures and
            # relative profiler clocks re-anchor onto the first comm
            # span below.
            off = float(clock_metadata(traces[rank])[1] or 0.0)
            shift = off - epoch
            dev_all = dev_comp + dev_comm
            if dev_all:
                first = min(t0 for t0, _t1 in dev_all) + shift
                anchor = (comm_iv[0][0] if comm_iv
                          else (windows[0][0] if windows else 0.0))
                if abs(first - anchor) > _CLOCK_SANITY_US:
                    shift += anchor - first
            comp_u = _iv_union([(a + shift, b + shift)
                                for a, b in dev_comp])
            dev_comm_shifted = [(a + shift, b + shift)
                                for a, b in dev_comm]
            if dev_comm_shifted:
                comm_iv = dev_comm_shifted
                # EXEC span remainders (host-side dispatch wait)
                # attribute to host once device comm is the comm truth
                host_iv = host_iv + [(t0, t1)
                                     for t0, t1, _i, _n in comm_sp]
            mode = "device"

        agg = {k: 0.0 for k in ("compute", "overlapped_comm",
                                "exposed_comm", "data_wait", "host",
                                "idle", "step_wall")}
        for w0, w1 in windows:
            if mode == "device":
                parts = decompose_window(
                    w0, w1, compute=comp_u, comm=comm_iv,
                    data=data_iv, host=host_iv)
            else:
                busy = _iv_union(comm_iv + data_iv + host_iv)
                inferred = _iv_subtract([(w0, w1)], busy)
                parts = decompose_window(
                    w0, w1, compute=inferred, comm=comm_iv,
                    data=data_iv, host=host_iv)
            for k in agg:
                agg[k] += parts[k]
        comm_total = agg["overlapped_comm"] + agg["exposed_comm"]
        per_rank[rank] = dict(
            {k: round(v, 1) for k, v in agg.items()},
            steps=max(len(windows), 0),
            mode=mode,
            overlap_fraction=(
                round(agg["overlapped_comm"] / comm_total, 4)
                if (mode == "device" and comm_total > 0) else None),
        )

        for t0, t1, tid, tensor in comm_sp:
            if mode == "device":
                exp = _iv_total(_iv_subtract([(t0, t1)], comp_u))
            else:
                exp = t1 - t0
            exposed_rows.append({
                "trace_id": tid, "tensor": tensor, "rank": rank,
                "exposed_us": round(exp, 1),
                "span_us": round(t1 - t0, 1),
            })

    exposed_rows.sort(key=lambda r: -r["exposed_us"])
    return {
        "trace_dir": trace_dir,
        "xplane": xplane_info,
        "ranks": ranks,
        "per_rank": per_rank,
        "top_exposed": exposed_rows[:top],
    }


def render_overlap(rep: dict) -> str:
    """Human-readable rendering of overlap()'s dict."""
    lines = [f"hvtputrace overlap — {rep['trace_dir']} "
             f"(ranks: {rep['ranks']})"]
    xp = rep["xplane"]
    if xp["status"] == "ok":
        lines.append(f"device profile: {xp['path']}")
    else:
        lines.append(
            f"device profile: none ({xp['status']}: {xp['reason']}) — "
            "host-only attribution: EXEC spans count as exposed comm, "
            "compute is inferred from non-span time")
    lines.append("")
    cols = ("compute", "overlapped_comm", "exposed_comm", "data_wait",
            "host", "idle")
    lines.append(
        f"  {'rank':>4}  {'steps':>5}  {'wall_ms':>9}  "
        + "  ".join(f"{c[:10]:>10}" for c in cols)
        + f"  {'overlap':>8}  {'mode':>9}")
    for rank in rep["ranks"]:
        row = rep["per_rank"][rank]
        frac = row["overlap_fraction"]
        pct = []
        wall = row["step_wall"] or 1.0
        for c in cols:
            pct.append(f"{row[c] / 1e3:7.2f}ms" if wall else "")
        lines.append(
            f"  {rank:>4}  {row['steps']:>5}  "
            f"{row['step_wall'] / 1e3:>9.2f}  "
            + "  ".join(f"{p:>10}" for p in pct)
            + f"  {'n/a' if frac is None else f'{frac:.2%}':>8}"
            + f"  {row['mode']:>9}")
    lines.append("")
    lines.append("top exposed collectives:")
    if not rep["top_exposed"]:
        lines.append("  (none)")
    for r in rep["top_exposed"]:
        lines.append(
            f"  {r['trace_id']} (rank {r['rank']}): "
            f"{r['exposed_us'] / 1e3:.2f} ms exposed of "
            f"{r['span_us'] / 1e3:.2f} ms span")
    return "\n".join(lines)


def render_report(rep: dict) -> str:
    """Human-readable rendering of report()'s dict."""
    lines = [f"hvtputrace report — {rep['trace_dir']} "
             f"(ranks: {rep['ranks']})", ""]
    lines.append("per-rank wait vs input vs compute:")
    lines.append(f"  {'rank':>4}  {'extent_ms':>10}  {'wait_ms':>10}  "
                 f"{'input_ms':>10}  {'compute_ms':>10}  {'wait%':>6}  "
                 f"{'input%':>6}  {'clk_off_us':>10}")
    for rank in rep["ranks"]:
        row = rep["per_rank"][rank]
        off = row["clock_offset_us"]
        data_wait_us = row.get("data_wait_us", 0.0)
        data_frac = row.get("data_wait_fraction", 0.0)
        lines.append(
            f"  {rank:>4}  {row['trace_extent_us'] / 1e3:>10.2f}  "
            f"{row['wait_us'] / 1e3:>10.2f}  "
            f"{data_wait_us / 1e3:>10.2f}  "
            f"{row['compute_us'] / 1e3:>10.2f}  "
            f"{row['wait_fraction'] * 100:>5.1f}%  "
            f"{data_frac * 100:>5.1f}%  "
            f"{'n/a' if off is None else f'{off:.0f}':>10}")
    lines.append("")
    lines.append("top stragglers (times last to arrive):")
    if not rep["stragglers"]:
        lines.append("  (no multi-rank collectives in trace)")
    for row in rep["stragglers"]:
        lines.append(
            f"  rank {row['rank']}: last {row['times_last']}x, "
            f"total skew {row['total_skew_us'] / 1e3:.2f} ms")
    lines.append("")
    lines.append("slowest collectives by arrival skew:")
    worst = sorted(rep["collectives"],
                   key=lambda c: -c["arrival_skew_us"])[:10]
    if not worst:
        lines.append("  (none)")
    for c in worst:
        lines.append(
            f"  {c['trace_id']}: rank {c['last_rank']} arrived "
            f"{c['arrival_skew_us'] / 1e3:.2f} ms after "
            f"rank {c['first_rank']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# postmortem merge (obs/flight.py dumps)
# ---------------------------------------------------------------------------

_POSTMORTEM_FILE_RE = re.compile(r"postmortem-(.+)-(\d+)\.json$")


def load_postmortems(dump_dir: str) -> List[dict]:
    """Every parseable ``postmortem-<rank>-<gen>.json`` in the dir,
    sorted by (generation, rank)."""
    docs = []
    for path in sorted(glob.glob(os.path.join(dump_dir,
                                              "postmortem-*.json"))):
        if not _POSTMORTEM_FILE_RE.search(os.path.basename(path)):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(doc, dict):
            doc["_path"] = path
            docs.append(doc)
    if not docs:
        raise FileNotFoundError(
            f"no postmortem-*.json files in {dump_dir!r} — postmortems "
            "are written by obs/flight.py on fatal paths (or SIGUSR2) "
            "into HVTPU_FLIGHT_DIR")
    def _key(d):
        r = d.get("rank")
        return (d.get("generation", 0),
                (0, r) if isinstance(r, int) else (1, str(r)))
    docs.sort(key=_key)
    return docs


def postmortem_merge(dump_dir: str) -> dict:
    """Fuse per-rank postmortems into one clock-corrected causal
    timeline.

    Each dump's events already carry wall-clock timestamps from its
    own rank's clock; the tracing handshake offset (``clock.offset_us``,
    rank0-relative) recorded in the dump corrects them onto rank 0's
    clock.  Ranks without an offset merge uncorrected (flagged in
    their summary row).
    """
    docs = load_postmortems(dump_dir)
    timeline: List[dict] = []
    per_rank: List[dict] = []
    for doc in docs:
        rank = doc.get("rank", "?")
        clk = doc.get("clock") or {}
        offset_us = clk.get("offset_us")
        shift = float(offset_us) / 1e6 if offset_us is not None else 0.0
        events = doc.get("events") or []
        per_rank.append({
            "rank": rank,
            "generation": doc.get("generation", 0),
            "reason": doc.get("reason"),
            "reasons": doc.get("reasons") or [],
            "t_wall": doc.get("t_wall"),
            "events": len(events),
            "clock_offset_us": offset_us,
            "clock_corrected": offset_us is not None,
            "path": doc.get("_path"),
        })
        for e in events:
            if not isinstance(e, dict):
                continue
            e = dict(e)
            t = e.pop("t_wall", None)
            kind = e.pop("kind", "?")
            timeline.append({
                "t": (float(t) + shift) if t is not None else 0.0,
                "rank": rank,
                "kind": kind,
                **e,
            })
    timeline.sort(key=lambda e: e["t"])
    return {
        "dump_dir": dump_dir,
        "ranks": [p["rank"] for p in per_rank],
        "per_rank": per_rank,
        "timeline": timeline,
    }


def render_postmortem(rep: dict, *, tail: int = 0) -> str:
    """Human-readable rendering of postmortem_merge()'s dict: the
    per-rank dump summary, then the merged timeline (all of it, or the
    last ``tail`` events)."""
    lines = [f"hvtputrace postmortem — {rep['dump_dir']} "
             f"(ranks: {rep['ranks']})", ""]
    lines.append("dumps:")
    for p in rep["per_rank"]:
        off = p["clock_offset_us"]
        corr = (f"offset {off:+.0f}us" if off is not None
                else "UNCORRECTED clock")
        lines.append(
            f"  rank {p['rank']} gen {p['generation']}: "
            f"reason={p['reason']} ({', '.join(p['reasons'])}), "
            f"{p['events']} events, {corr}")
    timeline = rep["timeline"]
    shown = timeline[-tail:] if tail and tail > 0 else timeline
    lines.append("")
    lines.append(f"timeline ({len(shown)} of {len(timeline)} events, "
                 "rank-0 clock):")
    if not timeline:
        lines.append("  (empty rings)")
        return "\n".join(lines)
    t0 = timeline[0]["t"]
    for e in shown:
        extras = " ".join(
            f"{k}={e[k]}" for k in sorted(e)
            if k not in ("t", "rank", "kind"))
        lines.append(
            f"  +{e['t'] - t0:10.6f}s  [rank {e['rank']}] "
            f"{e['kind']}" + (f"  {extras}" if extras else ""))
    return "\n".join(lines)
