"""hvtpu.fleet — multi-job resource arbiter over one elastic pool.

Gang scheduling (full min-world allocations only), priority preemption
through the graceful-drain channel (planned resizes, zero lost steps,
no restart-budget strikes), and traffic-driven autoscaling hooks.
See docs/fleet.md.
"""

from .arbiter import FleetArbiter
from .autoscale import Autoscaler, FileSignal
from .job import (DONE, DRAINING, FAILED, FleetSpecError, Job, JobSpec,
                  PENDING, RESIZING, RUNNING, STATES, prefixed_client)
from .runner import AllocationDiscovery, ElasticJobRunner

__all__ = [
    "FleetArbiter",
    "Autoscaler",
    "FileSignal",
    "FleetSpecError",
    "Job",
    "JobSpec",
    "prefixed_client",
    "AllocationDiscovery",
    "ElasticJobRunner",
    "STATES",
    "PENDING",
    "RUNNING",
    "DRAINING",
    "RESIZING",
    "DONE",
    "FAILED",
]
