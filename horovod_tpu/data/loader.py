"""ElasticDataLoader: checkpointable, resize-aware input with prefetch.

Delivery model
--------------
The loader owns a tiny :class:`LoaderState` — ``(epoch, cursor, seed)``,
the shuffle key and the **global** cursor of samples the world has
consumed this epoch.  Those three values plus the sharder's pure
functions (data/sharder.py) fully determine every future batch, so
registering the state object with an elastic ``State``
(``elastic.ObjectState(data=loader.state, ...)``) makes the iterator
checkpointable for free: ordinary commits, rollback restores, and the
graceful-preemption drain commit (core/preempt.py) all capture it, and
a relaunched incarnation — possibly with a different world size —
resumes mid-epoch by re-splitting the unconsumed remainder.  That is
the exactly-once contract: a sample is re-delivered only if the commit
that covered it was rolled back.

Prefetch
--------
A background thread plans ahead of the delivery cursor (across epoch
boundaries), fetches from the source, optionally ``jax.device_put``-s
the batch (``HVTPU_DATA_DEVICE_PUT``), and parks it in a bounded queue
(``HVTPU_DATA_PREFETCH_DEPTH``, default 2 — i.e. double buffering:
one batch on device feeding the current step, one in flight).  The
planner tags every batch with the state *version*; a restore bumps the
version, so stale prefetched batches are discarded at delivery and the
planner re-plans from the restored cursor — prefetched-but-undelivered
samples are never counted as consumed.

Coordinated epoch boundary
--------------------------
Steps-per-epoch is a pure function of shared state, so ranks agree on
the boundary without communication — *if* they agree on the sample
count.  For sources whose length could skew across hosts (file lists
over eventually-consistent storage), the first use in each incarnation
runs an allreduce-MIN over ``len(source)`` (``HVTPU_DATA_COORD_BOUNDARY``,
default on) and every rank trains on the agreed prefix; a short shard
therefore never deadlocks peers.  The epoch's ragged tail is split
evenly (pieces differ by <= 1, possibly empty); loops that run a
collective per batch route empty tails through ``hvt.join()``.

Observability: ``hvtpu_data_*`` metrics (docs/observability.md), a
``DATA_WAIT`` trace phase so ``hvtputrace report`` attributes
stragglers to input vs compute vs comms, loader state in ``/debug``,
and the ``data.next`` fault site (delay/error/drop) for chaos runs.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..core import faults
from ..obs import metrics as obs_metrics
from ..obs import stepprof
from ..obs import tracing
from .sharder import Sharder
from .sources import DataSource, map_structure

logger = logging.getLogger("horovod_tpu")

_M_WAIT = obs_metrics.histogram(
    "hvtpu_data_wait_seconds",
    "Time the training loop blocked waiting on the input pipeline per "
    "batch (the data-stall half of the straggler decomposition).",
    buckets=obs_metrics.DEFAULT_TIME_BUCKETS)
_M_QDEPTH = obs_metrics.gauge(
    "hvtpu_data_queue_depth",
    "Prefetch queue depth sampled at each batch delivery (0 means the "
    "consumer is outrunning the producer — input-bound).")
_M_SAMPLES = obs_metrics.counter(
    "hvtpu_data_samples_delivered_total",
    "Samples delivered to this rank's training loop.")
_M_BATCHES = obs_metrics.counter(
    "hvtpu_data_batches_delivered_total",
    "Batches delivered to this rank's training loop.")
_M_RESHARDS = obs_metrics.counter(
    "hvtpu_data_reshards_total",
    "Iterator-state restores applied (elastic resync / rollback): each "
    "re-partitions the unconsumed epoch remainder across the world.")

# live loaders for the /debug endpoint and the pre-exit quiesce hook
_LIVE: Dict[str, "ElasticDataLoader"] = {}
_LIVE_LOCK = threading.Lock()


def _debug_state() -> dict:
    with _LIVE_LOCK:
        loaders = list(_LIVE.items())
    return {name: ld.debug_state() for name, ld in loaders}


def quiesce_all() -> None:
    """Stop every live loader's prefetch thread (state is untouched).
    Called by the graceful-preemption path right before a drain exit so
    no thread is mid-``device_put`` when the process leaves."""
    with _LIVE_LOCK:
        loaders = list(_LIVE.values())
    for ld in loaders:
        try:
            ld.quiesce()
        except Exception:  # pragma: no cover - shutdown must not raise
            logger.debug("data loader quiesce failed", exc_info=True)


class LoaderState:
    """The checkpointable iterator state: ``epoch``, the global
    ``cursor`` (samples the WORLD consumed this epoch — rank-agnostic,
    so the elastic sync broadcast cannot desync it), and the shuffle
    ``seed``.  Implements both the hvtpu elastic participant protocol
    (``hvtpu_state_dict``/``hvtpu_load_state_dict``, applied IN PLACE by
    ``ObjectState`` so the loader's reference stays live) and the
    torch-style ``state_dict``/``load_state_dict`` pair (``TorchState``
    captures it as a handle)."""

    def __init__(self, seed: int = 0):
        self.epoch = 0
        self.cursor = 0
        self.seed = int(seed)
        # bumped on every restore so the prefetch planner re-plans and
        # stale prefetched batches are discarded at delivery
        self.version = 0

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": int(self.epoch), "cursor": int(self.cursor),
                "seed": int(self.seed)}

    def load_state_dict(self, sd: Dict[str, int]) -> None:
        self.epoch = int(sd["epoch"])
        self.cursor = int(sd["cursor"])
        self.seed = int(sd.get("seed", self.seed))
        self.version += 1
        _M_RESHARDS.inc()

    # elastic participant protocol (horovod_tpu/elastic/state.py)
    hvtpu_state_dict = state_dict
    hvtpu_load_state_dict = load_state_dict

    def __repr__(self):
        return (f"LoaderState(epoch={self.epoch}, cursor={self.cursor}, "
                f"seed={self.seed})")


class _Item:
    """One prefetched batch, tagged with the plan version and the
    cursor window it covers."""

    __slots__ = ("version", "epoch", "cursor_before", "cursor_after",
                 "indices", "batch", "error")

    def __init__(self, version, epoch, cursor_before, cursor_after,
                 indices, batch, error=None):
        self.version = version
        self.epoch = epoch
        self.cursor_before = cursor_before
        self.cursor_after = cursor_after
        self.indices = indices
        self.batch = batch
        self.error = error


def _env_flag(raw: Optional[str], default: bool) -> bool:
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


class ElasticDataLoader:
    """Elastic-aware sharded loader over a :class:`DataSource`.

    Usage (JAX, mirrors the reference's ElasticSampler shape)::

        loader = ElasticDataLoader(ArraySource({"x": x, "y": y}),
                                   batch_size=64, seed=1234)
        state = elastic.JaxState(params=params, data=loader.state)

        @elastic.run
        def train(state):
            while loader.state.epoch < EPOCHS:
                for batch in loader:      # resumes mid-epoch on resize
                    ...per-rank batch of exactly batch_size samples...
                state.commit()

    Per step every rank receives ``batch_size`` samples (the world
    consumes ``size * batch_size``), so per-rank batch shapes — and
    hence compiled programs — are invariant across resizes.
    """

    def __init__(self, source: DataSource, batch_size: int, *,
                 seed: int = 0, shuffle: bool = True,
                 prefetch_depth: Optional[int] = None,
                 device_put: Optional[bool] = None,
                 transform: Optional[Callable[[Any], Any]] = None,
                 with_indices: bool = False,
                 name: str = "default"):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.source = source
        self.batch_size = int(batch_size)
        self.shuffle = bool(shuffle)
        self.transform = transform
        self.with_indices = bool(with_indices)
        self.name = name
        self.state = LoaderState(seed=seed)
        if prefetch_depth is None:
            prefetch_depth = int(os.environ.get(
                "HVTPU_DATA_PREFETCH_DEPTH", "2"))
        self.prefetch_depth = max(1, int(prefetch_depth))
        if device_put is None:
            device_put = _env_flag(
                os.environ.get("HVTPU_DATA_DEVICE_PUT", "1"), True)
        self._device_put = bool(device_put)
        self._coord_boundary = _env_flag(
            os.environ.get("HVTPU_DATA_COORD_BOUNDARY", "1"), True)
        self._queue: "queue.Queue[_Item]" = queue.Queue(
            maxsize=self.prefetch_depth)
        self._lock = threading.Lock()
        self._plan_epoch = 0  # hvtpulint: guarded-by(_lock)
        self._plan_cursor = 0  # hvtpulint: guarded-by(_lock)
        self._plan_version = -1  # hvtpulint: guarded-by(_lock)
        self._pending_error: Optional[BaseException] = None  # hvtpulint: guarded-by(_lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rank = 0
        self._size = 1
        self._n: Optional[int] = None
        self._sharder: Optional[Sharder] = None
        self._delivered_batches = 0
        self._delivered_samples = 0
        self._register()

    # -- world / length agreement ---------------------------------------
    def _agreed_length(self) -> int:
        """The sample count every rank trains on this incarnation.
        Resolved lazily at first use (after ``hvt.init`` and the
        elastic sync): an allreduce-MIN over the local ``len(source)``
        when the world has peers, so a short shard bounds the epoch for
        everyone instead of deadlocking them at its end."""
        if self._n is not None:
            return self._n
        n_local = len(self.source)
        n = n_local
        from ..core import state as core_state

        st = core_state.global_state()
        if st.initialized:
            self._rank, self._size = st.rank, st.size
        if st.initialized and st.size > 1 and self._coord_boundary:
            import jax.numpy as jnp

            import horovod_tpu as hvt

            agreed = int(np.asarray(hvt.allreduce(
                jnp.asarray([n_local], dtype=jnp.int32), op=hvt.Min,
                name=f"hvtpu.data.len.{self.name}"))[0])
            if agreed != n_local:
                logger.warning(
                    "data loader %r: local source has %d samples but the "
                    "world agreed on %d (allreduce-min); the last %d are "
                    "ignored this incarnation", self.name, n_local,
                    agreed, n_local - agreed)
            n = agreed
        if n <= 0:
            raise ValueError(
                f"data loader {self.name!r}: agreed sample count is {n}")
        self._n = n
        self._sharder = Sharder(n, self.batch_size,
                                seed=self.state.seed, shuffle=self.shuffle)
        return n

    def steps_per_epoch(self) -> int:
        """Batches per full epoch — identical on every rank."""
        self._agreed_length()
        return self._sharder.steps_remaining(0, self._size)

    def __len__(self) -> int:
        return self.steps_per_epoch()

    # -- prefetch thread -------------------------------------------------
    def _ensure_started(self) -> None:
        self._agreed_length()
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._prefetch_loop,
            name=f"hvtpu-data-prefetch-{self.name}", daemon=True)
        self._thread.start()

    def _prefetch_loop(self) -> None:
        n = self._n
        sharder = self._sharder
        while not self._stop.is_set():
            with self._lock:
                if self._plan_version != self.state.version:
                    # restore/rollback: re-plan from the delivery state;
                    # stale queue items are discarded by version at
                    # delivery, so no draining is needed here
                    self._plan_version = self.state.version
                    self._plan_epoch = self.state.epoch
                    self._plan_cursor = self.state.cursor
                    sharder = Sharder(
                        n, self.batch_size, seed=self.state.seed,
                        shuffle=self.shuffle)
                if self._plan_cursor >= n:
                    self._plan_epoch += 1
                    self._plan_cursor = 0
                version = self._plan_version
                epoch = self._plan_epoch
                cursor = self._plan_cursor
            try:
                indices, new_cursor = sharder.next_indices(
                    epoch, cursor, self._rank, self._size)
                batch = self.source.fetch(indices)
                if self.transform is not None:
                    batch = self.transform(batch)
                if self._device_put:
                    batch = self._to_device(batch)
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                with self._lock:
                    self._pending_error = e
                return
            item = _Item(version, epoch, cursor, new_cursor, indices,
                         batch)
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.2)
                    break
                except queue.Full:
                    continue
            with self._lock:
                if self._plan_version == version:
                    self._plan_cursor = new_cursor

    def _to_device(self, batch):
        try:
            import jax

            return jax.device_put(batch)
        except Exception:
            logger.warning(
                "data loader %r: device_put failed; delivering host "
                "batches from now on", self.name, exc_info=True)
            self._device_put = False
            return batch

    # -- delivery ---------------------------------------------------------
    def _next_item(self) -> _Item:
        """Take the next in-plan batch, discarding stale (pre-restore)
        prefetches and surfacing producer errors."""
        while True:
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                with self._lock:
                    err = self._pending_error
                    self._pending_error = None
                if err is not None:
                    raise RuntimeError(
                        f"data loader {self.name!r}: prefetch failed"
                    ) from err
                if self._stop.is_set() or self._thread is None \
                        or not self._thread.is_alive():
                    raise RuntimeError(
                        f"data loader {self.name!r}: prefetch thread is "
                        "not running (closed mid-iteration?)")
                continue
            if item.version != self.state.version:
                continue  # prefetched before a restore: never deliver
            return item

    def _deliver(self) -> Tuple[np.ndarray, Any]:
        t0 = time.perf_counter()
        t_wall0 = time.time()
        if tracing.ACTIVE:
            tracing.op_begin(f"data/{self.name}", kind="data",
                             phase=tracing.DATA_WAIT,
                             epoch=self.state.epoch,
                             cursor=self.state.cursor)
        try:
            dropped = False
            if faults.ACTIVE:
                # delay stalls inside the DATA_WAIT span (an injected
                # input straggler); error raises; drop loses one batch
                dropped = faults.inject(
                    "data.next",
                    detail=f"{self.name}@{self.state.epoch}:"
                           f"{self.state.cursor}")
            item = self._next_item()
            if dropped:
                logger.warning(
                    "data loader %r: injected drop lost batch "
                    "epoch=%d cursor=%d (%d samples)", self.name,
                    item.epoch, item.cursor_before, len(item.indices))
                self.state.cursor = item.cursor_after
                item = self._next_item()
        finally:
            if tracing.ACTIVE:
                tracing.op_done(f"data/{self.name}")
            if stepprof.ACTIVE:
                # Wall-clock window for the overlap profiler's
                # per-step data-wait bucket (obs/stepprof).
                stepprof.note_data_wait(t_wall0, time.time())
        _M_WAIT.observe(time.perf_counter() - t0)
        if item.cursor_before != self.state.cursor \
                or item.epoch != self.state.epoch:
            raise RuntimeError(
                f"data loader {self.name!r}: prefetch plan diverged "
                f"from delivery state (planned {item.epoch}:"
                f"{item.cursor_before}, expected {self.state.epoch}:"
                f"{self.state.cursor})")
        self.state.cursor = item.cursor_after
        self._delivered_batches += 1
        self._delivered_samples += len(item.indices)
        _M_BATCHES.inc()
        _M_SAMPLES.inc(len(item.indices))
        _M_QDEPTH.set(self._queue.qsize())
        return item.indices, item.batch

    def __iter__(self):
        """Yield the CURRENT epoch's remaining batches (mid-epoch
        resume after a restore is automatic: the cursor says where to
        pick up), then advance ``state.epoch`` so a per-epoch
        ``state.commit()`` captures the rollover."""
        self._ensure_started()
        n = self._agreed_length()
        epoch = self.state.epoch
        while self.state.epoch == epoch and self.state.cursor < n:
            indices, batch = self._deliver()
            yield (indices, batch) if self.with_indices else batch
        if self.state.epoch == epoch and self.state.cursor >= n:
            self.state.epoch += 1
            self.state.cursor = 0

    def stream(self):
        """Infinite batch iterator across epoch boundaries (the bench
        shape: the prefetcher keeps the queue full through rollovers)."""
        while True:
            yield from self

    # -- lifecycle ---------------------------------------------------------
    def _register(self) -> None:
        with _LIVE_LOCK:
            base, k = self.name, 1
            while self.name in _LIVE:
                self.name = f"{base}-{k}"
                k += 1
            first = not _LIVE
            _LIVE[self.name] = self
        if first:
            obs_metrics.register_debug_provider("data", _debug_state)

    def quiesce(self) -> None:
        """Stop the prefetch thread; state and the registration stay
        (iteration restarts the thread)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            # unblock a producer parked on a full queue
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)
        self._thread = None

    def close(self) -> None:
        """Quiesce and deregister (no dangling thread — unit-tested)."""
        self.quiesce()
        with _LIVE_LOCK:
            _LIVE.pop(self.name, None)
            empty = not _LIVE
        if empty:
            obs_metrics.unregister_debug_provider("data")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection ------------------------------------------------------
    def debug_state(self) -> dict:
        t = self._thread
        return {
            "epoch": self.state.epoch,
            "cursor": self.state.cursor,
            "seed": self.state.seed,
            "samples": self._n,
            "batch_size": self.batch_size,
            "rank": self._rank,
            "size": self._size,
            "queue_depth": self._queue.qsize(),
            "prefetch_depth": self.prefetch_depth,
            "prefetch_alive": bool(t is not None and t.is_alive()),
            "device_put": self._device_put,
            "delivered_batches": self._delivered_batches,
            "delivered_samples": self._delivered_samples,
        }
