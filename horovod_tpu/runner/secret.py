"""Per-job HMAC signing of the runner's function channel.

Parity surface: ``horovod/runner/common/util/secret.py`` — the
reference generates a per-job secret and signs every driver/task
service message so a pickled payload is only loaded if its HMAC
verifies.  Here the signed artifacts are the two pickle files of the
programmatic ``run()`` API: the shipped function blob and each rank's
result blob — both cross a filesystem (and, on the ssh path, a remote
host), and unpickling unverified bytes is arbitrary code execution.

Wire format: ``HMAC_SHA256(key, blob) || blob`` (32-byte digest
prefix).  The key travels to workers in ``HVTPU_SECRET_KEY`` (parity:
the reference passes its secret through the env of spawned workers).
"""

from __future__ import annotations

import hmac
import os
import secrets as _secrets

ENV_KEY = "HVTPU_SECRET_KEY"
# Path-indirection variant: the env carries only the PATH of a 0600
# key file, never the key itself — ssh serializes the worker env into
# its argv, and argv is world-readable via /proc/*/cmdline, which
# would hand every local user the forging key.  run() uses the file
# form; ENV_KEY remains for single-machine/manual invocations.
ENV_KEY_FILE = "HVTPU_SECRET_FILE"
DIGEST_BYTES = 32


class SignatureError(RuntimeError):
    """A signed blob failed verification — fail closed, never unpickle."""


def make_secret_key() -> str:
    return _secrets.token_hex(32)


def _key_bytes(key: str) -> bytes:
    return key.encode("ascii")


def sign(key: str, blob: bytes) -> bytes:
    """``digest || blob`` ready to write."""
    digest = hmac.new(_key_bytes(key), blob, "sha256").digest()
    return digest + blob


def verify(key: str, signed: bytes) -> bytes:
    """Return the payload iff the digest checks out; raise otherwise."""
    if len(signed) < DIGEST_BYTES:
        raise SignatureError("signed blob shorter than its digest")
    digest, blob = signed[:DIGEST_BYTES], signed[DIGEST_BYTES:]
    want = hmac.new(_key_bytes(key), blob, "sha256").digest()
    if not hmac.compare_digest(digest, want):
        raise SignatureError(
            "HMAC signature mismatch on runner payload; refusing to "
            "unpickle (tampered or foreign file)"
        )
    return blob


def write_key_file(key: str, path: str) -> None:
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(key)


def require_env_key() -> str:
    path = os.environ.get(ENV_KEY_FILE, "")
    if path:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError as e:
            raise SignatureError(
                f"cannot read {ENV_KEY_FILE}={path!r}: {e}"
            ) from None
    key = os.environ.get(ENV_KEY, "")
    if not key:
        raise SignatureError(
            f"neither {ENV_KEY_FILE} nor {ENV_KEY} is set; the "
            "runner's function channel is signed per job and workers "
            "refuse unsigned payloads"
        )
    return key
