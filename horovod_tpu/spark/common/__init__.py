"""Shared estimator infrastructure (reference: horovod/spark/common/)."""

from .backend import Backend, LocalBackend, SparkBackend  # noqa: F401
from .estimator import HorovodEstimator, HorovodModel  # noqa: F401
from .params import EstimatorParams  # noqa: F401
from .store import FilesystemStore, LocalStore, Store  # noqa: F401
