"""Torch DistributedOptimizer (parity: horovod/torch/optimizer.py
``_DistributedOptimizer`` / ``DistributedOptimizer``).

Same contract as the reference: wrap any ``torch.optim.Optimizer``;
per-parameter hooks fire as autograd accumulates each grad and launch
an async (optionally compressed) allreduce through the eager
mini-controller — so communication of early layers overlaps backward of
later layers exactly like the reference's background thread; ``step()``
synchronizes all handles, writes averaged grads back, then runs the
wrapped optimizer's math locally.

Supports ``backward_passes_per_step`` local aggregation,
``op=Average/Sum/Adasum``, ``gradient_predivide_factor``, process sets,
and ``skip_synchronize()``.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import torch

import horovod_tpu as _hvt

from . import mpi_ops
from .compression import Compression


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1,
                 op=None, gradient_predivide_factor: float = 1.0,
                 process_set=None, sparse_as_dense: bool = False):
        super(self.__class__, self).__init__(params)
        op = mpi_ops.Average if op is None else op
        if gradient_predivide_factor != 1.0 and op != mpi_ops.Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average"
            )
        self._sparse_as_dense = sparse_as_dense
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self._predivide = gradient_predivide_factor
        self.backward_passes_per_step = backward_passes_per_step

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
        name_of = {id(p): n for n, p in named}

        self._parameter_names = {}
        self._handles = {}
        self._grad_accs = []
        self._requires_update = []
        self._synchronized = False
        self._should_synchronize = True
        self._passes = {}

        idx = 0
        for group in self.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                self._parameter_names[p] = name_of.get(
                    id(p), f"allreduce.noname.{idx}"
                )
                idx += 1
                self._requires_update.append(p)
                self._passes[p] = 0
                self._register_hook(p)

    # -- hook plumbing ----------------------------------------------------
    def _register_hook(self, p: torch.nn.Parameter):
        if hasattr(p, "register_post_accumulate_grad_hook"):
            p.register_post_accumulate_grad_hook(self._make_post_hook(p))
        else:  # pragma: no cover - old torch
            # Reference trick: hook the grad accumulator node
            # (horovod/torch/optimizer.py _register_hooks).
            tmp = p.expand_as(p)
            grad_acc = tmp.grad_fn.next_functions[0][0]
            grad_acc.register_hook(self._make_acc_hook(p))
            self._grad_accs.append(grad_acc)

    def _make_post_hook(self, p):
        def hook(param):
            self._on_grad_ready(p)
        return hook

    def _make_acc_hook(self, p):  # pragma: no cover - old torch
        def hook(*ignore):
            self._on_grad_ready(p)
        return hook

    def _on_grad_ready(self, p):
        if self._handles.get(p) is not None:
            raise AssertionError(
                "Gradients were computed more than "
                "backward_passes_per_step times before call to step(). "
                "Increase backward_passes_per_step to accumulate more."
            )
        self._passes[p] += 1
        if self._passes[p] == self.backward_passes_per_step:
            # Declare the burst to the controller's coalescing gate:
            # this step will stream one allreduce per registered param,
            # but the gaps between hooks are paced by backward compute,
            # so the gate's quiet-gap heuristic alone mis-splits the
            # burst under load (novel fusion shapes -> recompiles, and
            # the schedule predictor never sees a stable pattern).
            self._hint_burst()
            self._handles[p] = self._allreduce_grad_async(p)

    def _hint_burst(self):
        from horovod_tpu.eager import get_controller

        try:
            get_controller().hint_burst(len(self._requires_update))
        except Exception:
            pass  # gate hint only; never fail a backward over it

    def _allreduce_grad_async(self, p):
        name = self._parameter_names[p]
        grad = p.grad
        if grad.is_sparse:
            if self._sparse_as_dense:
                grad = grad.to_dense()
                p.grad = grad  # in-place allreduce target must be dense
            else:
                # parity: sparse grads route through the values+indices
                # allgather (sparse_allreduce_async); predivide is a
                # dense-path feature in the reference too.
                if self._predivide != 1.0:
                    raise ValueError(
                        "gradient_predivide_factor is not supported "
                        "with sparse gradients (use sparse_as_dense)"
                    )
                return mpi_ops.sparse_allreduce_async(
                    grad, name=f"allreduce.{name}", op=self._op,
                    process_set=self._process_set,
                )
        if self._predivide != 1.0:
            prescale = 1.0 / self._predivide
            # Average over the ranks that actually participate: the
            # process set's size when one is supplied, else the world.
            n = (self._process_set.size if self._process_set is not None
                 else _hvt.size())
            postscale = self._predivide / n
            op = mpi_ops.Sum
        else:
            prescale, postscale, op = 1.0, 1.0, self._op
        return mpi_ops.allreduce_async_(
            grad, name=f"allreduce.{name}", op=op,
            compression=self._compression,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=self._process_set,
        )

    # -- public contract --------------------------------------------------
    def set_backward_passes_per_step(self, passes: int):
        self.backward_passes_per_step = passes
        for p in self._passes:
            self._passes[p] = 0

    def synchronize(self):
        """Wait for all outstanding grad allreduces; grads are updated
        in place (the *_async_ in-place contract)."""
        for p in self._requires_update:
            handle = self._handles.get(p)
            if handle is None:
                # Hook never fired (conditionally-unused param, or a
                # partial accumulation when step() arrives early).  The
                # reference allreduces EVERY registered param here
                # (optimizer.py synchronize's missing_p loop) — ranks
                # that didn't touch the param contribute zeros; skipping
                # instead would desync the collective schedule and hang
                # the other ranks.
                if p.grad is None:
                    p.grad = torch.zeros_like(p)
                handle = self._allreduce_grad_async(p)
                self._handles[p] = handle
            result = mpi_ops.synchronize(handle)
            if isinstance(handle, mpi_ops.SparseAllreduceHandle):
                # sparse results can't land in-place; replace the grad
                # (parity: p.grad = synchronize(handle) for sparse)
                p.grad = result
        self._handles.clear()
        for p in self._passes:
            self._passes[p] = 0
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Run step() without synchronizing (caller already did; parity:
        optimizer.skip_synchronize() in the reference)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called without a preceding "
                    "backward; called synchronize() twice"
                )
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, set_to_none: bool = True):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition."
            )
        return super(self.__class__, self).zero_grad(set_to_none)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op=None,
                         gradient_predivide_factor: float = 1.0,
                         process_set=None,
                         sparse_as_dense: bool = False
                         ) -> torch.optim.Optimizer:
    """Wrap ``optimizer`` for data-parallel training (parity:
    hvd.DistributedOptimizer for torch).

    Dynamically subclasses the optimizer's own class (same trick as
    horovod/torch/optimizer.py) so isinstance checks and hyperparameter
    access keep working.
    """
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               process_set, sparse_as_dense)
