"""Pallas TPU kernels for the data-plane hot ops.

TPU-native re-expression of the reference's hand-written device kernels
(``horovod/common/ops/cuda/cuda_kernels.cu``: the batched
scale-buffer fp16/fp32 kernels used around fused collectives, and the
pack/unpack memcpys of ``collective_operations.cc
MemcpyInFusionBuffer/MemcpyOutFusionBuffer``).  On TPU the XLA compiler
already fuses most elementwise work, so these kernels target the two
places where an explicit kernel still wins:

* ``fused_scale_cast`` — one-pass ``cast(x * scale)`` over a flat
  fusion buffer: a single HBM read + write at the *output* width even
  when scale forces an f32 intermediate (XLA sometimes materialises the
  f32 product when the producer/consumer live in different fusions —
  e.g. across a collective boundary, exactly where this runs).
* ``quantize_int8_blocks`` / ``dequantize_int8_blocks`` — per-block
  absmax int8 (de)quantisation for the EQuARX-style quantized-wire
  allreduce (comm/quantized.py), with optional stochastic rounding via
  the on-core PRNG (cuda_kernels.cu's scale kernels have no TPU analog
  in XLA's standard fusion set for the rounding path).

Every entry point falls back to a numerically-identical XLA lowering
when not running on TPU (CPU tests, interpret-unfriendly shapes), so
callers never need to branch.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but keep the import soft for safety
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover - pallas ships with jax
    _HAS_PALLAS = False

# Lane width of the VPU / MXU; last-dim tiles are always 128 wide.
_LANES = 128
# Rows per grid step for the flat-buffer kernels: 256 rows x 128 lanes
# x 4 B = 128 KiB per operand block in VMEM — small enough to double
# buffer, large enough to saturate HBM bandwidth.
_TILE_ROWS = 256


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _pallas_mode() -> Tuple[bool, bool]:
    """(use_pallas, interpret).  HVTPU_PALLAS=0 disables the kernels
    entirely; HVTPU_PALLAS_INTERPRET=1 forces the Pallas path in
    interpreter mode so CPU tests execute the real kernel bodies."""
    import os

    if not _HAS_PALLAS or os.environ.get("HVTPU_PALLAS", "1") == "0":
        return False, False
    if os.environ.get("HVTPU_PALLAS_INTERPRET", "0") == "1":
        return True, True
    return _on_tpu(), False


def _pad_to_grid(flat, rows_mult: int) -> Tuple[jax.Array, int, int]:
    """Pad a 1-D buffer and reshape to (rows, _LANES) with rows a
    multiple of ``rows_mult``; returns (2-D view, rows, original n)."""
    n = flat.shape[0]
    per_block = rows_mult * _LANES
    padded = ((n + per_block - 1) // per_block) * per_block
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    rows = padded // _LANES
    return flat.reshape(rows, _LANES), rows, n


def _split_rows(rows: int) -> Tuple[int, int]:
    """(main_rows, rem_rows): full _TILE_ROWS tiles + one remainder.

    Keeps padding at the _QROWS granularity (1024 elements — the wire
    block) instead of padding every buffer up to a full 256-row tile,
    which would inflate small tensors' wire size up to 32x.  The
    remainder runs as a second single-program pallas call with
    full-array blocks (Mosaic allows sub-(8,128) blocks only when they
    equal the whole array)."""
    rem = rows % _TILE_ROWS
    return rows - rem, rem


# ----------------------------------------------------------------------
# fused scale + cast
# ----------------------------------------------------------------------


def _scale_cast_kernel(scale_ref, x_ref, out_ref):
    # scale lives in SMEM as (1, 1); the multiply runs in f32 and the
    # narrowing cast happens in-register before the VMEM write, so HBM
    # sees only in-dtype reads and out-dtype writes.
    s = scale_ref[0, 0]
    out_ref[:] = (x_ref[:].astype(jnp.float32) * s).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def _scale_cast_xla(flat, scale, out_dtype):
    return (flat.astype(jnp.float32) * scale).astype(out_dtype)


def fused_scale_cast(flat, scale, out_dtype=None):
    """``cast(flat * scale)`` in one pass over a flat buffer.

    Parity: the scale-buffer CUDA kernels the reference launches around
    fused collectives for prescale/postscale
    (``horovod/common/ops/cuda/cuda_kernels.cu``, dispatched from
    ``ScaleBuffer`` in gpu_operations.cc).

    Args:
      flat: 1-D array (any float/int dtype).
      scale: python float or 0-D array.
      out_dtype: output dtype (defaults to ``flat.dtype``).
    """
    out_dtype = jnp.dtype(out_dtype or flat.dtype)
    use, interp = _pallas_mode()
    if not use or flat.ndim != 1:
        return _scale_cast_xla(jnp.asarray(flat), float(scale), out_dtype)

    x2, rows, n = _pad_to_grid(jnp.asarray(flat), _QROWS)
    scale_arr = jnp.full((1, 1), scale, jnp.float32)

    def call(x_part, part_rows, tile):
        return pl.pallas_call(
            _scale_cast_kernel,
            grid=(part_rows // tile,),
            interpret=interp,
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((part_rows, _LANES), out_dtype),
        )(scale_arr, x_part)

    main, rem = _split_rows(rows)
    parts = []
    if main:
        parts.append(call(x2[:main], main, _TILE_ROWS))
    if rem:
        parts.append(call(x2[main:], rem, rem))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out.reshape(-1)[:n]


# ----------------------------------------------------------------------
# int8 block quantize / dequantize
# ----------------------------------------------------------------------

# Quantisation block = one (8, 128) f32 tile = 1024 elements; each
# block carries one f32 absmax scale (0.4% wire overhead).
_QROWS = 8
QBLOCK = _QROWS * _LANES


def block_scale_inv(xg):
    """Shared absmax-block quantisation formula: (scale, inv) for
    blocks ``xg (g, B) f32``.  THE single definition — the Pallas
    kernel, the XLA twin, and the ring kernel's per-hop requantization
    (ops/ring.py) must stay bit-identical, so they all call this."""
    absmax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
    # single multiply (not /127): a division invites per-fusion
    # strength-reduction ulp drift between lowerings
    scale = absmax * jnp.float32(1.0 / 127.0)
    inv = jnp.where(scale > 0.0,
                    1.0 / jnp.where(scale > 0.0, scale, 1.0), 0.0)
    return scale, inv


def _quantize_kernel(seed_ref, x_ref, q_ref, scale_ref, *, stochastic,
                     tile):
    i = pl.program_id(0)
    if stochastic:
        pltpu.prng_seed(seed_ref[0] + i)
    x = x_ref[:].astype(jnp.float32)              # (tile, 128)
    # per-(8,128)-tile absmax: reduce within each group of _QROWS rows
    g = tile // _QROWS
    xg = x.reshape(g, _QROWS * _LANES)
    scale, inv = block_scale_inv(xg)
    scaled = xg * inv
    if stochastic:
        # pltpu.stochastic_round only targets bf16/fp8; integer
        # stochastic rounding is floor(x + u), u ~ U[0,1) from the
        # on-core PRNG (top 24 bits -> exact f32 uniform): unbiased,
        # E[q] = x, so quantisation noise cancels across summed ranks.
        bits = pltpu.bitcast(
            pltpu.prng_random_bits(scaled.shape), jnp.uint32)
        # route via int32 (Mosaic has no uint32->f32 cast); >>9 keeps
        # 23 bits, safely positive in int32
        u = ((bits >> 9).astype(jnp.int32).astype(jnp.float32)
             * jnp.float32(1.0 / (1 << 23)))
        q = jnp.clip(jnp.floor(scaled + u), -127, 127).astype(jnp.int8)
    else:
        q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    q_ref[:] = q.reshape(tile, _LANES)
    scale_ref[:] = scale


def _dequantize_kernel(q_ref, scale_ref, out_ref, *, tile):
    g = tile // _QROWS
    q = q_ref[:].astype(jnp.float32).reshape(g, _QROWS * _LANES)
    out = q * scale_ref[:]
    out_ref[:] = out.reshape(tile, _LANES).astype(out_ref.dtype)


def _quantize_xla(flat):
    x2, rows, n = _pad_to_grid(flat.astype(jnp.float32), _QROWS)
    g = rows // _QROWS
    xg = x2.reshape(g, QBLOCK)
    scale, inv = block_scale_inv(xg)
    q = jnp.clip(jnp.round(xg * inv), -127, 127).astype(jnp.int8)
    return q.reshape(rows, _LANES), scale, n


def quantize_int8_blocks(flat, *, stochastic: bool = False,
                         seed=0):
    """Block-absmax int8 quantisation of a flat f32/bf16 buffer.

    Returns ``(codes, scales, n)``: codes ``(rows, 128) int8`` (rows a
    multiple of 8, zero-padded), scales ``(rows/8, 1) f32`` — one per
    1024-element block — and the original element count ``n``.

    ``stochastic=True`` uses the on-core PRNG for unbiased rounding
    (recommended when the quantized wire feeds a summation, as in the
    EQuARX reduce-scatter phase — rounding bias accumulates over ranks).
    """
    flat = jnp.asarray(flat)
    use, interp = _pallas_mode()
    if stochastic and interp:
        # the on-core PRNG has no interpreter implementation
        stochastic = False
    if not use or flat.ndim != 1:
        q, scale, n = _quantize_xla(flat)
        return q, scale, n

    # keep the native width into the kernel (the in-register cast in
    # the body handles f32 accumulation) — a host-side astype would
    # materialize a full f32 copy of the buffer in HBM first; only
    # exotic dtypes (f64 etc.) pre-cast
    if flat.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16):
        flat = flat.astype(jnp.float32)
    x2, rows, n = _pad_to_grid(flat, _QROWS)

    def call(x_part, part_rows, tile, seed_val):
        g_per_tile = tile // _QROWS
        # seed_val may be a traced scalar (see compression._stochastic_seed)
        seed_arr = jnp.asarray(seed_val, jnp.int32).reshape(1)
        return pl.pallas_call(
            functools.partial(_quantize_kernel, stochastic=stochastic,
                              tile=tile),
            grid=(part_rows // tile,),
            interpret=interp,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((g_per_tile, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((part_rows, _LANES), jnp.int8),
                jax.ShapeDtypeStruct((part_rows // _QROWS, 1),
                                     jnp.float32),
            ),
        )(seed_arr, x_part)

    main, rem = _split_rows(rows)
    qs, ss = [], []
    if main:
        q, s = call(x2[:main], main, _TILE_ROWS, seed)
        qs.append(q)
        ss.append(s)
    if rem:
        # distinct seed stream for the remainder program
        q, s = call(x2[main:], rem, rem, seed + main // _TILE_ROWS + 1)
        qs.append(q)
        ss.append(s)
    if len(qs) == 1:
        return qs[0], ss[0], n
    return jnp.concatenate(qs), jnp.concatenate(ss), n


def dequantize_int8_blocks(q, scale, n: int, dtype=jnp.float32):
    """Inverse of :func:`quantize_int8_blocks` → 1-D array of length n."""
    q = jnp.asarray(q)
    scale = jnp.asarray(scale)
    rows = q.shape[0]
    use, interp = _pallas_mode()
    if not use or rows % _QROWS != 0:
        g = rows // _QROWS
        out = (q.astype(jnp.float32).reshape(g, QBLOCK) * scale)
        return out.reshape(-1)[:n].astype(dtype)

    def call(q_part, s_part, part_rows, tile):
        g_per_tile = tile // _QROWS
        return pl.pallas_call(
            functools.partial(_dequantize_kernel, tile=tile),
            grid=(part_rows // tile,),
            interpret=interp,
            in_specs=[
                pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((g_per_tile, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile, _LANES), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((part_rows, _LANES), dtype),
        )(q_part, s_part)

    main, rem = _split_rows(rows)
    parts = []
    if main:
        parts.append(call(q[:main], scale[: main // _QROWS], main,
                          _TILE_ROWS))
    if rem:
        parts.append(call(q[main:], scale[main // _QROWS:], rem, rem))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out.reshape(-1)[:n]
