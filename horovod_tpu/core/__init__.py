from .config import Config
from .exceptions import (
    HorovodInternalError,
    HorovodTpuError,
    HostsUpdatedInterrupt,
    HvtpuDivergenceError,
    HvtpuMismatchError,
    NotInitializedError,
    StallError,
)
from .process_set import ProcessSet, ProcessSetTable
from .state import (
    GlobalState,
    add_process_set,
    global_state,
    init,
    initialized,
    remove_process_set,
    require_init,
    shutdown,
)
from .topology import DCN_AXIS, ICI_AXIS, PROC_AXIS, WORLD_AXIS, Topology

__all__ = [
    "Config",
    "HorovodInternalError",
    "HorovodTpuError",
    "HostsUpdatedInterrupt",
    "HvtpuDivergenceError",
    "HvtpuMismatchError",
    "NotInitializedError",
    "StallError",
    "ProcessSet",
    "ProcessSetTable",
    "GlobalState",
    "add_process_set",
    "global_state",
    "init",
    "initialized",
    "remove_process_set",
    "require_init",
    "shutdown",
    "Topology",
    "WORLD_AXIS",
    "DCN_AXIS",
    "ICI_AXIS",
    "PROC_AXIS",
]
