"""obs/stepprof.py + hvtputrace overlap — measured overlap profiling.

Covers (ISSUE PR 12): the six-way interval-algebra decomposition and
its ``sum(parts) == step_wall`` invariant over synthetic interval sets
(full/zero/partial/multi-stream overlap), the hardened xplane loader
(absent/empty/truncated -> explicit status, never an IndexError
mid-varint), the device-profile join against the checked-in fixture
xplane, the runtime collector's metrics, measured MFU provenance via
``cost_analysis()``, and the 2-proc acceptance where an injected
pre-collective delay on rank 1 surfaces as rank-0 *exposed* comm in
``python -m tools.hvtputrace overlap``.
"""

import json
import os

import pytest

import horovod_tpu
from horovod_tpu.obs import metrics as obs_metrics
from horovod_tpu.obs import profile, stepprof, tracing
from horovod_tpu.runner import run
from tools import hvtputrace

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_FIXTURE_XPLANE = os.path.join(_REPO_ROOT, "tests", "fixtures")
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}

_PART_KEYS = ("compute", "overlapped_comm", "exposed_comm",
              "data_wait", "host", "idle")


def _sum_parts(parts):
    return sum(parts[k] for k in _PART_KEYS)


# --------------------------------------------------------------------------
# interval algebra
# --------------------------------------------------------------------------

class TestIntervalAlgebra:
    def test_union_merges_overlaps_and_sorts(self):
        assert stepprof.union([(5, 7), (0, 2), (1, 3), (7, 7)]) \
            == [(0, 3), (5, 7)]

    def test_intersect(self):
        assert stepprof.intersect([(0, 4), (6, 10)], [(3, 7)]) \
            == [(3, 4), (6, 7)]

    def test_subtract(self):
        assert stepprof.subtract([(0, 10)], [(2, 3), (5, 7)]) \
            == [(0, 2), (3, 5), (7, 10)]

    def test_clip_and_total(self):
        assert stepprof.clip([(0, 4), (3, 8)], 2, 6) == [(2, 6)]
        assert stepprof.total([(0, 2), (5, 8)]) == 5


class TestDecompose:
    """The six-way split's invariant across overlap regimes."""

    def test_full_overlap(self):
        p = stepprof.decompose(0, 10, compute=[(0, 10)], comm=[(2, 6)])
        assert p["overlapped_comm"] == 4
        assert p["exposed_comm"] == 0
        assert p["compute"] == 6
        assert p["overlap_fraction"] == 1.0
        assert _sum_parts(p) == p["step_wall"] == 10

    def test_zero_overlap(self):
        p = stepprof.decompose(0, 10, compute=[(0, 4)], comm=[(5, 9)])
        assert p["overlapped_comm"] == 0
        assert p["exposed_comm"] == 4
        assert p["overlap_fraction"] == 0.0
        assert p["idle"] == 2
        assert _sum_parts(p) == 10

    def test_partial_overlap(self):
        p = stepprof.decompose(0, 10, compute=[(0, 6)], comm=[(4, 8)])
        assert p["overlapped_comm"] == 2
        assert p["exposed_comm"] == 2
        assert p["compute"] == 4
        assert p["overlap_fraction"] == 0.5
        assert _sum_parts(p) == 10

    def test_multi_stream_overlap(self):
        """Several comm streams + fragmented compute: union semantics,
        not per-stream double counting."""
        p = stepprof.decompose(
            0, 20,
            compute=[(0, 5), (8, 12), (15, 20)],
            comm=[(3, 9), (4, 10), (11, 16)],   # overlapping streams
            data=[(9, 11)], host=[(5, 8)])
        # comm union [3,10)+[11,16) = 12; compute covers [3,5)+[8,10)+
        # [11,12)+[15,16) of it -> overlapped 6, exposed 6
        assert p["overlapped_comm"] == 6
        assert p["exposed_comm"] == 6
        assert p["data_wait"] == 0  # [9,11) is inside comm
        assert _sum_parts(p) == pytest.approx(p["step_wall"])

    def test_priority_comm_then_data_then_host(self):
        p = stepprof.decompose(
            0, 10, comm=[(0, 4)], data=[(2, 6)], host=[(5, 8)])
        assert p["exposed_comm"] == 4
        assert p["data_wait"] == 2   # [4,6): the part outside comm
        assert p["host"] == 2        # [6,8): outside comm+data
        assert p["idle"] == 2
        assert _sum_parts(p) == 10

    def test_no_comm_has_null_fraction(self):
        p = stepprof.decompose(0, 5, compute=[(0, 5)])
        assert p["overlap_fraction"] is None
        assert _sum_parts(p) == 5

    def test_windows_clip_to_step(self):
        p = stepprof.decompose(10, 20, compute=[(0, 12)], comm=[(18, 40)])
        assert p["compute"] == 2
        assert p["exposed_comm"] == 2
        assert _sum_parts(p) == 10

    def test_tool_decompose_matches_runtime(self):
        """hvtputrace carries a jax-free mirror of the decomposition;
        the two implementations must agree bucket for bucket."""
        cases = [
            dict(compute=[(0, 6)], comm=[(4, 8)], data=[(8, 9)],
                 host=[(9, 10)]),
            dict(compute=[(0, 5), (8, 12), (15, 20)],
                 comm=[(3, 9), (4, 10), (11, 16)], data=[(9, 11)],
                 host=[(5, 8)]),
            dict(comm=[(1, 2)], host=[(0, 20)]),
            dict(),
        ]
        for kw in cases:
            a = stepprof.decompose(0, 20, **kw)
            b = hvtputrace.decompose_window(0, 20, **kw)
            for k in _PART_KEYS + ("step_wall",):
                assert a[k] == pytest.approx(b[k]), (k, kw)

    def test_exposed_span_blame(self):
        comp = stepprof.union([(0, 4), (6, 8)])
        assert stepprof.exposed_span((2, 7), comp) == 2  # [4,6)


# --------------------------------------------------------------------------
# hardened xplane loader (satellite: CPU-only CI must not raise)
# --------------------------------------------------------------------------

class TestLoadProfile:
    def test_absent_dir_is_no_profile(self, tmp_path):
        res = profile.load_profile(str(tmp_path / "nope"))
        assert res["status"] == "no-profile"
        assert "xplane" in res["reason"]

    def test_zero_byte_file_is_empty(self, tmp_path):
        (tmp_path / "x.xplane.pb").write_bytes(b"")
        res = profile.load_profile(str(tmp_path))
        assert res["status"] == "empty"

    def test_truncated_file_is_explicit_not_indexerror(self, tmp_path):
        with open(os.path.join(_FIXTURE_XPLANE,
                               "stepprof.xplane.pb"), "rb") as f:
            good = f.read()
        for cut in (1, 7, len(good) // 2, len(good) - 1):
            (tmp_path / "x.xplane.pb").write_bytes(good[:cut])
            res = profile.load_profile(str(tmp_path))
            assert res["status"] in ("truncated", "empty"), cut
        # the raising API raises a *clean* error, not IndexError
        with pytest.raises(ValueError):
            profile.op_summary(str(tmp_path))

    def test_fixture_intervals_and_comm_classification(self):
        res = profile.load_profile(_FIXTURE_XPLANE)
        assert res["status"] == "ok"
        ivs = res["planes"]["/device:TPU:0"]
        assert [(iv["t0_us"], iv["t1_us"], iv["comm"]) for iv in ivs] \
            == [(0.0, 400.0, False), (300.0, 700.0, True),
                (600.0, 1000.0, False)]

    def test_comm_op_regex(self):
        for name in ("all-reduce.1", "all-gather-start",
                     "reduce-scatter.3", "collective-permute.7",
                     "fusion.all_reduce.2", "AllReduce"):
            assert profile.is_comm_op(name), name
        for name in ("fusion.23", "convolution.1", "ascend.2",
                     "recvive"):  # no bare-substring false positives
            assert not profile.is_comm_op(name), name

    def test_summary_still_raises_on_missing(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            profile.op_summary(str(tmp_path))


# --------------------------------------------------------------------------
# runtime collector + device join
# --------------------------------------------------------------------------

class TestCollector:
    @pytest.fixture(autouse=True)
    def fresh(self):
        stepprof.reset()
        yield
        stepprof.reset()

    def test_step_boundary_observes_exposed_comm(self):
        import time as _time

        c = stepprof.get_collector()
        before = _hist_cells("hvtpu_step_exposed_comm_seconds")
        c.note_step_boundary()           # opens the window
        _time.sleep(0.015)               # comm must land inside it
        now = _time.time()
        c.note_comm("g", now - 0.010, now - 0.004, nbytes=64)
        c.note_comm("h", now - 0.006, now - 0.002, nbytes=64)
        c.note_step_boundary()
        after = _hist_cells("hvtpu_step_exposed_comm_seconds")
        assert after["count"] == before["count"] + 1
        # union [t-10ms, t-2ms] = 8 ms, not 6+4
        assert 0.004 < after["sum"] - before["sum"] < 0.5

    def test_mfu_gauge_from_step_flops(self):
        c = stepprof.get_collector()
        c.set_step_flops(stepprof.peak_flops() * 0.01)  # 1% of peak/s
        c.note_step_boundary()
        import time as _time
        _time.sleep(0.01)
        c.note_step_boundary()
        v = stepprof.MFU.value()
        assert v > 0

    def test_debug_state_shape(self):
        stepprof.install()
        try:
            from horovod_tpu.obs.metrics import debug_snapshot
            dbg = debug_snapshot()
            assert "stepprof" in dbg
            st = dbg["stepprof"]
            for key in ("active", "steps", "peak_tflops", "mfu",
                        "overlap_fraction", "last_step"):
                assert key in st
        finally:
            stepprof.uninstall()

    def test_join_device_profile_fixture(self):
        res = stepprof.join_device_profile(
            _FIXTURE_XPLANE, window=(0.0, 1000e-6))
        assert res["status"] == "ok"
        # fixture: comm [300,700), compute [0,400)+[600,1000) ->
        # 200 us overlapped, 200 us exposed
        assert res["overlap_fraction"] == pytest.approx(0.5)
        assert res["exposed_comm_s"] == pytest.approx(200e-6)
        assert res["overlapped_comm_s"] == pytest.approx(200e-6)
        assert stepprof.OVERLAP_FRACTION.value() == pytest.approx(0.5)

    def test_join_degrades_without_profile(self, tmp_path):
        res = stepprof.join_device_profile(str(tmp_path))
        assert res["status"] == "no-profile"
        assert res["overlap_fraction"] is None

    def test_align_device_intervals(self):
        ivs = [{"t0_us": 5.0, "t1_us": 7.0, "comm": True}]
        out, shift = stepprof.align_device_intervals(ivs, 1e15)
        assert shift == pytest.approx(1e15 - 5.0)
        assert out[0]["t0_us"] == pytest.approx(1e15)
        # wall-like timestamps pass through unshifted
        out2, shift2 = stepprof.align_device_intervals(ivs, 10.0)
        assert shift2 == 0.0 and out2 is ivs


def _hist_cells(name):
    fam = obs_metrics.snapshot().get(name) or {"values": {}}
    cells = fam["values"].values()
    return {"count": sum(c["count"] for c in cells),
            "sum": sum(c["sum"] for c in cells)}


class TestMeasuredFlops:
    def test_cost_analysis_provenance(self):
        """The MFU numerator comes from the compiled program itself."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(a, b):
            return a @ b

        spec = jax.ShapeDtypeStruct((64, 64), jnp.float32)
        flops = stepprof.measured_flops(f.lower(spec, spec).compile())
        if flops is None:
            pytest.skip("backend exposes no cost analysis")
        # 2*M*N*K with some tolerance for backend accounting
        assert 64 ** 3 < flops < 8 * 64 ** 3
        assert stepprof.mfu(flops, 1.0) == pytest.approx(
            flops / stepprof.peak_flops())

    def test_measured_flops_tolerates_junk(self):
        class NoCA:
            def cost_analysis(self):
                raise NotImplementedError

        class ListCA:
            def cost_analysis(self):
                return [{"flops": 42.0}]

        assert stepprof.measured_flops(NoCA()) is None
        assert stepprof.measured_flops(ListCA()) == 42.0


# --------------------------------------------------------------------------
# hvtputrace overlap (offline tool)
# --------------------------------------------------------------------------

def _write_rank_trace(path, events):
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(events))


def _synthetic_rank0(tmp_path, *, with_boundaries=True):
    """One rank: step window [0, 1000) us, EXEC span [250, 750),
    matching the fixture xplane's comm [300,700) / compute
    [0,400)+[600,1000)."""
    evs = [
        {"name": "clock_anchor", "ph": "i", "ts": 0, "pid": 0, "tid": 0,
         "args": {"wall_t0_us": 0}},
        {"name": "clock_offset", "ph": "i", "ts": 0, "pid": 0, "tid": 0,
         "args": {"offset_us": 0.0, "error_bound_us": 1.0}},
        {"name": "EXEC", "cat": "tensor", "ph": "B", "ts": 250.0,
         "pid": 0, "tid": 5,
         "args": {"trace_id": "g#0", "tensor": "g"}},
        {"name": "EXEC", "ph": "E", "ts": 750.0, "pid": 0, "tid": 5},
    ]
    if with_boundaries:
        evs += [
            {"name": "step_boundary", "ph": "i", "ts": 0.0, "pid": 0,
             "tid": 0, "args": {"wall_us": 0.0, "steps": 1}},
            {"name": "step_boundary", "ph": "i", "ts": 1000.0, "pid": 0,
             "tid": 0, "args": {"wall_us": 1000.0, "steps": 1}},
        ]
    _write_rank_trace(str(tmp_path / "rank0.trace.json"), evs)
    return str(tmp_path)


class TestOverlapTool:
    def test_device_join_decomposition(self, tmp_path):
        trace_dir = _synthetic_rank0(tmp_path)
        rep = hvtputrace.overlap(trace_dir, xplane_dir=_FIXTURE_XPLANE)
        assert rep["xplane"]["status"] == "ok"
        row = rep["per_rank"][0]
        assert row["mode"] == "device"
        assert row["overlapped_comm"] == pytest.approx(200.0)
        assert row["exposed_comm"] == pytest.approx(200.0)
        assert row["compute"] == pytest.approx(600.0)
        assert row["overlap_fraction"] == pytest.approx(0.5)
        assert _sum_parts(row) == pytest.approx(row["step_wall"])
        # blame: the EXEC span's exposed share is the non-compute part
        assert rep["top_exposed"][0]["trace_id"] == "g#0"
        assert rep["top_exposed"][0]["exposed_us"] == pytest.approx(200.0)

    def test_degrades_gracefully_without_xplane(self, tmp_path):
        trace_dir = _synthetic_rank0(tmp_path)
        rep = hvtputrace.overlap(trace_dir)
        row = rep["per_rank"][0]
        assert row["mode"] == "host-only"
        assert row["overlapped_comm"] == 0.0
        assert row["exposed_comm"] == pytest.approx(500.0)  # EXEC span
        assert row["compute"] == pytest.approx(500.0)       # inferred
        assert row["overlap_fraction"] is None
        assert _sum_parts(row) == pytest.approx(row["step_wall"])
        text = hvtputrace.render_overlap(rep)
        assert "host-only" in text and "g#0" in text

    def test_extent_fallback_without_boundaries(self, tmp_path):
        trace_dir = _synthetic_rank0(tmp_path, with_boundaries=False)
        rep = hvtputrace.overlap(trace_dir)
        row = rep["per_rank"][0]
        assert row["step_wall"] > 0
        assert _sum_parts(row) == pytest.approx(row["step_wall"])

    def test_cli_overlap(self, tmp_path, capsys):
        from tools.hvtputrace.__main__ import main

        trace_dir = _synthetic_rank0(tmp_path)
        assert main(["overlap", trace_dir, "--xplane", _FIXTURE_XPLANE,
                     "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["per_rank"]["0"]["overlap_fraction"] \
            == pytest.approx(0.5)
        assert main(["overlap", trace_dir]) == 0
        assert "overlap" in capsys.readouterr().out


# --------------------------------------------------------------------------
# tracing integration: boundaries + predict confirmation instants
# --------------------------------------------------------------------------

class TestTracingIntegration:
    def test_note_step_emits_boundary_instant(self, tmp_path):
        stepprof.reset()
        tracing.install(str(tmp_path), rank=0, size=1)
        try:
            obs_metrics.note_step(examples=8, steps=2)
            obs_metrics.note_step(examples=8, steps=2)
        finally:
            tracing.uninstall()
        with open(tmp_path / "rank0.trace.json") as f:
            evs = json.load(f)
        bounds = [e for e in evs if e.get("name") == "step_boundary"]
        assert len(bounds) == 2
        assert bounds[0]["args"]["steps"] == 2
        assert bounds[0]["args"]["wall_us"] > 0

    def test_allreduce_done_carries_wall_window(self, tmp_path,
                                                monkeypatch):
        """comm/eager's DONE instant carries the device-joinable wall
        window, and the collector records the same dispatch."""
        import jax.numpy as jnp

        stepprof.reset()
        monkeypatch.setenv("HVTPU_TRACE", str(tmp_path))
        horovod_tpu.init()
        try:
            horovod_tpu.allreduce(jnp.ones((16,), jnp.float32))
        finally:
            horovod_tpu.shutdown()
        with open(tmp_path / "rank0.trace.json") as f:
            evs = json.load(f)
        done = [e for e in evs if e.get("name") == "DONE"]
        assert done, "no DONE instant traced"
        args = done[0]["args"]
        assert args["wall_t1_us"] >= args["wall_t0_us"] > 0
        with stepprof.get_collector()._lock:
            assert len(stepprof.get_collector()._comm) >= 1


# --------------------------------------------------------------------------
# 2-process acceptance: injected delay -> rank-0 exposed comm
# --------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.multiprocess
def test_overlap_acceptance_2proc(tmp_path):
    """`python -m tools.hvtputrace overlap` on a 2-proc run with a
    50 ms pre-collective delay on rank 1: every rank's six parts sum
    to its step wall, rank 0's exposed comm absorbs the peer's delay,
    and the delayed collective tops the exposed list."""

    trace_dir = str(tmp_path)

    def body():
        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.obs import metrics as _m

        hvt.init()
        _m.note_step(steps=1)  # opens the first step window
        for _ in range(3):
            hvt.allreduce(jnp.ones((1024,), jnp.float32))
            _m.note_step(steps=1)
        hvt.shutdown()
        return "ok"

    env = dict(
        _ENV,
        HVTPU_TRACE=trace_dir,
        HVTPU_FAULT_SPEC="collective.pre:delay(50)@rank=1",
    )
    assert run(body, np=2, cpu_devices=1, env=env,
               start_timeout=300.0) == ["ok", "ok"]

    rep = hvtputrace.overlap(trace_dir)
    assert rep["ranks"] == [0, 1]
    for r in (0, 1):
        row = rep["per_rank"][r]
        assert _sum_parts(row) == pytest.approx(row["step_wall"],
                                                rel=1e-6, abs=1.0)
    # rank 0 dispatches on time and then waits out rank 1's injected
    # 50 ms delay inside its EXEC spans: exposed comm > 2 x 50 ms
    # across the 3 collectives (host-only mode: EXEC == exposed).
    assert rep["per_rank"][0]["exposed_comm"] > 100_000.0
    # rank 1 is the skewed rank: it arrives late (the delay burns
    # outside its spans), so its own exposed comm stays well below
    # rank 0's wait time
    assert rep["per_rank"][1]["exposed_comm"] \
        < rep["per_rank"][0]["exposed_comm"]
    # the delayed allreduce is blamed by name in the top-N list
    assert rep["top_exposed"]
    assert rep["top_exposed"][0]["tensor"].startswith("allreduce")
    assert rep["top_exposed"][0]["exposed_us"] > 40_000.0
    # CLI end to end
    from tools.hvtputrace.__main__ import main

    assert main(["overlap", trace_dir]) == 0
