"""Pod-shape training: P processes × D local devices, ONE global mesh.

The deployment shape of a real TPU pod (e.g. v5e-256 = 64 hosts × 4
chips): every process runs the SAME jitted training step over the
global ``hvt.world_mesh()`` (multi-controller JAX), each providing its
locally-addressable shards.  The jit/SPMD path uses ALL P×D devices;
``hvt.rank()``/``size()`` stay process-granularity (one Horovod rank =
one process, exactly like the reference's one-rank-per-GPU model, with
D chips per rank instead of one).

Run (2 processes × 4 virtual CPU devices = an 8-device global mesh):

    hvtpurun -np 2 --cpu-devices 4 python examples/pod_train.py

On real TPU hosts, drop ``--cpu-devices`` — each process picks up its
host's chips and the mesh spans the slice.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=256, help="global batch")
    args = p.parse_args()

    hvt.init()
    mesh = hvt.world_mesh()
    n_dev = mesh.devices.size
    if hvt.rank() == 0:
        print(f"pod: {hvt.size()} processes x "
              f"{jax.local_device_count()} local devices = "
              f"{n_dev}-device world mesh", flush=True)

    # Deterministic synthetic data; every process generates the full
    # array and contributes only the shards it owns.
    rng = np.random.RandomState(0)
    W0 = (rng.randn(64, 8) * 0.1).astype(np.float32)
    X = rng.randn(args.batch, 64).astype(np.float32)
    Y = rng.randn(args.batch, 8).astype(np.float32)

    repl = NamedSharding(mesh, P())
    rows = NamedSharding(mesh, P("world"))
    w = jax.make_array_from_callback(W0.shape, repl, lambda i: W0[i])
    x = jax.make_array_from_callback(X.shape, rows, lambda i: X[i])
    y = jax.make_array_from_callback(Y.shape, rows, lambda i: Y[i])

    opt = hvt.DistributedOptimizer(
        optax.sgd(0.1, momentum=0.9), axis_name="world"
    )

    def step(w, s, xs, ys):
        def loss_fn(w):
            return jnp.mean((xs @ w - ys) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(w)
        updates, s = opt.update(g, s, w)
        return optax.apply_updates(w, updates), s, \
            jax.lax.pmean(loss, "world")

    sstep = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("world"), P("world")),
        out_specs=(P(), P(), P()), check_vma=False,
    ))
    s = jax.jit(
        opt.init,
        out_shardings=jax.tree_util.tree_map(
            lambda _: repl, jax.eval_shape(opt.init, w)
        ),
    )(w)

    first = last = None
    for i in range(args.steps):
        w, s, loss = sstep(w, s, x, y)
        val = float(np.asarray(loss.addressable_data(0)))
        first = val if first is None else first
        last = val
    assert last < first, (first, last)
    if hvt.rank() == 0:
        print(f"loss {first:.4f} -> {last:.4f} over {args.steps} steps "
              f"on {n_dev} devices; ranks consistent "
              f"({hvt.size()} ranks)", flush=True)


if __name__ == "__main__":
    main()
