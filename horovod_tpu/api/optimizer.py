"""DistributedOptimizer: gradient averaging woven into the optimizer.

Parity surface: ``horovod/torch/optimizer.py`` (``_DistributedOptimizer``
— per-parameter hooks firing async allreduce during backward,
``synchronize()`` before ``step()``, ``backward_passes_per_step`` local
aggregation, ``op=Average/Sum/Adasum``, compression,
``gradient_predivide_factor``) and the TF ``DistributedOptimizer`` /
``DistributedGradientTape`` (horovod/tensorflow/__init__.py).

TPU-native design: the torch version needs hooks because gradients
materialize one at a time during eager backward, and a background thread
overlaps their reduction with remaining compute.  Under jit, XLA's
latency-hiding scheduler already overlaps the fused-bucket ``psum``s
with the backward computation — so the whole hook machinery collapses
into a gradient transformation: ``DistributedOptimizer(tx)`` is an
``optax.GradientTransformation`` that bucket-fuses and allreduces the
gradient tree (one wire-cast + one psum per bucket, deterministic
order — the FusionBufferManager semantics) before handing it to the
wrapped optimizer.  Inside jit/shard_map it lowers to ICI collectives;
outside it falls back to the eager process-level data plane.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..comm import eager as eager_comm
from ..comm.compression import NoneCompressor
from ..comm.fusion import fused_tree_allreduce, plan_buckets
from ..comm.reduce_ops import ReduceOp, normalize_op
from ..core import state as core_state
from ..core.exceptions import HorovodInternalError
from ..obs import metrics as obs_metrics

_M_NONFINITE = obs_metrics.counter(
    "hvtpu_optimizer_nonfinite_skips_total",
    "Optimizer updates guarded because the REDUCED gradients carried "
    "non-finite values (coordinated across ranks: every rank sees the "
    "same reduced tensors, so every rank skips/zeros/aborts together).")


def _nonfinite_action() -> str:
    """``HVTPU_NONFINITE_ACTION``: what every rank does, together, when
    the reduced gradients carry NaN/inf — skip (default) | zero |
    abort | off.

    The decision is *piggybacked on the gradient allreduce*: IEEE
    non-finites propagate through sum/average reduction, so checking
    the REDUCED gradients is a coordinated test — all ranks see the
    identical reduced tensors and reach the identical verdict with no
    extra collective.  This is what prevents the classic desync where
    one rank's local overflow makes it skip a step its peers apply."""
    v = os.environ.get("HVTPU_NONFINITE_ACTION", "skip").strip().lower()
    if v in ("", "skip"):
        return "skip"
    if v in ("off", "none", "disable", "disabled"):
        return "off"
    if v in ("zero", "abort"):
        return v
    raise ValueError(
        "HVTPU_NONFINITE_ACTION must be one of skip|zero|abort|off, "
        f"got {v!r}")


def _tree_finite(tree):
    """Scalar all-leaves-finite flag (traced-safe; integer leaves are
    finite by construction and skipped)."""
    flags = [
        jnp.all(jnp.isfinite(leaf))
        for leaf in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)
    ]
    if not flags:
        return jnp.asarray(True)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def _zero_nonfinite(tree):
    """Replace non-finite elements with zeros (float leaves only)."""
    return jax.tree_util.tree_map(
        lambda leaf: (
            jnp.where(jnp.isfinite(leaf), leaf, jnp.zeros_like(leaf))
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)
            else leaf
        ),
        tree,
    )


def allreduce_gradients(
    grads,
    *,
    axis_name: Optional[str] = None,
    op=None,
    average=None,
    compression=NoneCompressor,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    fusion_threshold_bytes: Optional[int] = None,
    process_set=None,
):
    """Fused allreduce of a gradient pytree.

    ``axis_name`` set → in-jit SPMD reduction over that mesh axis (the
    hot path).  ``axis_name=None`` → eager process-level reduction, with
    the same deterministic bucket plan so both paths agree with the
    reference's fused execution order (Controller::FuseResponses).
    """
    rop = normalize_op(op, average)
    st = core_state.global_state()
    # The tuner only participates when it actually chose the threshold —
    # an explicit fusion_threshold_bytes must neither be overridden nor
    # feed scores for candidates that were never in effect.  Restricted
    # to SINGLE-PROCESS worlds: at P>1 the eager controller owns tuning
    # (rank 0 scores, result broadcast in the ResponseList); a per-rank
    # tuner here would diverge ranks' bucket plans (different flattened
    # shapes for the same named collective) and double-count bytes on
    # rank 0.
    use_autotune = (
        fusion_threshold_bytes is None
        and st.initialized and st.autotuner is not None
        and axis_name is None and st.size == 1
    )
    if fusion_threshold_bytes is None:
        if use_autotune:
            # Autotuned threshold (eager path only: the jit path's fusion
            # is a compile-time constant, so retuning it would recompile
            # per candidate).  Parity: ParameterManager adjusting
            # HOROVOD_FUSION_THRESHOLD online.
            fusion_threshold_bytes = st.autotuner.current[0]
        elif st.initialized and st.config:
            fusion_threshold_bytes = st.config.fusion_threshold_bytes
        else:
            fusion_threshold_bytes = 64 * 1024 * 1024

    if axis_name is not None:
        groups = None
        if process_set is not None:
            ps = process_set
            if isinstance(ps, int):
                ps = core_state.require_init(
                    "process_set collectives"
                ).process_set_table.get(ps)
            groups = ps.device_groups()
        return fused_tree_allreduce(
            grads,
            axis_name=axis_name,
            threshold_bytes=fusion_threshold_bytes,
            op=rop,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            compression=compression,
            groups=groups,
        )

    # Eager path: bucket leaves deterministically, one eager allreduce
    # per fused flat buffer.
    from ..comm.packing import pack_flat, unpack_flat

    leaves_with_paths = jax.tree_util.tree_leaves_with_path(grads)
    names = [jax.tree_util.keystr(p) for p, _ in leaves_with_paths]
    leaves = [l for _, l in leaves_with_paths]
    treedef = jax.tree_util.tree_structure(grads)
    plan = plan_buckets(names, leaves, fusion_threshold_bytes)
    out = [None] * len(leaves)
    total_bytes = 0
    for k, bucket in enumerate(plan.buckets):
        if rop == ReduceOp.ADASUM:
            # Adasum's dot-product correction is per-tensor (reference:
            # tensor_counts in adasum.h DispatchFusedAllreduce keeps
            # segment boundaries inside the fused buffer); the eager
            # data plane has no segment support, so execute unfused —
            # results must not depend on the fusion threshold.
            for e in bucket:
                out[e.index] = eager_comm.allreduce(
                    leaves[e.index],
                    op=rop,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    compression=compression,
                    process_set=process_set,
                    name=f"adasum.{e.name}",
                )
                total_bytes += e.nbytes
            continue
        flat, _ = pack_flat([leaves[e.index] for e in bucket])
        red = eager_comm.allreduce(
            flat,
            op=rop,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            compression=compression,
            process_set=process_set,
            name=f"allreduce.bucket_{k}",
        )
        total_bytes += sum(e.nbytes for e in bucket)
        specs = [(e.shape, e.dtype, e.size) for e in bucket]
        for e, o in zip(bucket, unpack_flat(red, specs)):
            out[e.index] = o
    if use_autotune:
        st.autotuner.record_step(total_bytes)
    # Step telemetry for the eager reduction path (the jit path's
    # update is traced once, so its host loop reports via
    # metrics.note_step directly — see bench.py).
    obs_metrics.note_step()
    return jax.tree_util.tree_unflatten(treedef, out)


def ShardedDistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name: str,
    average: bool = True,
    compression=NoneCompressor,
) -> optax.GradientTransformation:
    """ZeRO-1-style sharded optimizer: reduce-scatter the gradients,
    run the inner optimizer on this rank's 1/N shard of the flattened
    parameter vector, then all-gather the updates.

    Post-parity TPU extension (SURVEY.md §2.7 lists sharded optimizers
    as absent from the reference; its ``reducescatter`` primitive —
    ``EnqueueTensorReducescatter`` — is exactly the ZeRO building
    block).  Optimizer state lives at 1/N per device: for Adam on a
    P-parameter model this drops per-device state from 2P to 2P/N.
    Wire cost per step is the same as allreduce (reduce_scatter +
    all_gather is how XLA lowers a large psum anyway).

    Both ``init`` and ``update`` must run inside ``jax.shard_map`` over
    ``axis_name`` (they call ``lax.axis_index``); init the state with a
    jitted shard_map too, using ``P(axis_name)``-sharded out_specs so
    the shards actually live distributed.

    Restriction: the inner optimizer must be *elementwise* (sgd,
    momentum, adam(w), rmsprop, ...) — the shard is a flat slice that
    ignores tensor boundaries, so per-tensor-structure transforms
    (adafactor's factored moments, per-leaf masks) are not supported.
    """
    from jax import lax as _lax

    from ..comm import spmd as _spmd
    from ..comm.packing import pack_flat, unpack_flat
    from ..comm.spmd import _is_int8

    if _is_int8(compression):
        # int8's per-block scales don't survive a raw summed wire (the
        # same guard spmd.allreduce and the eager controller apply);
        # the quantized path needs per-hop requantization, which the
        # reduce_scatter here does not do.
        raise ValueError(
            "ShardedDistributedOptimizer does not support int8 "
            "compression; use fp16/bf16"
        )

    def _flatten(tree):
        leaves_with_paths = jax.tree_util.tree_leaves_with_path(tree)
        leaves = [l for _, l in leaves_with_paths]
        flat, specs = pack_flat(leaves)
        return flat, specs, jax.tree_util.tree_structure(tree)

    def _shard_bounds(n_total, n_ranks):
        chunk = -(-n_total // n_ranks)  # ceil
        return chunk, chunk * n_ranks - n_total

    def init_fn(params):
        flat, _, _ = _flatten(params)
        n = _lax.axis_size(axis_name)
        chunk, pad = _shard_bounds(flat.shape[0], n)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        idx = _lax.axis_index(axis_name)
        mine = _lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)
        return optimizer.init(mine)

    def update_fn(grads, state, params=None, **extra):
        gflat, specs, treedef = _flatten(grads)
        n = _lax.axis_size(axis_name)
        chunk, pad = _shard_bounds(gflat.shape[0], n)
        if pad:
            gflat = jnp.pad(gflat, (0, pad))
        # wire compression rides the reduce_scatter like the fused
        # allreduce path's compressors
        wire, cctx = compression.compress(gflat)
        gshard = _spmd.reducescatter(
            wire.reshape(n, chunk), axis_name=axis_name,
            op=ReduceOp.AVERAGE if average else ReduceOp.SUM,
        ).reshape(chunk)
        gshard = compression.decompress(gshard, cctx)
        pshard = None
        if params is not None:
            pflat, _, _ = _flatten(params)
            if pad:
                pflat = jnp.pad(pflat, (0, pad))
            idx = _lax.axis_index(axis_name)
            pshard = _lax.dynamic_slice_in_dim(pflat, idx * chunk, chunk)
        upd_shard, new_state = optimizer.update(
            gshard.astype(gflat.dtype), state, pshard, **extra
        )
        full = _spmd.allgather(upd_shard, axis_name=axis_name)
        full = full.reshape(-1)
        if pad:
            full = full[:-pad]
        outs = unpack_flat(full, specs)
        return jax.tree_util.tree_unflatten(treedef, outs), new_state

    return optax.GradientTransformation(init_fn, update_fn)


class _DistOptState(NamedTuple):
    inner: optax.OptState
    acc: optax.Updates          # local gradient accumulator
    step_in_cycle: jnp.ndarray  # int32 counter for backward_passes_per_step


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name: Optional[str] = None,
    op=None,
    average=None,
    compression=NoneCompressor,
    backward_passes_per_step: int = 1,
    average_aggregated_gradients: bool = True,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    gradient_predivide_factor: float = 1.0,
    fusion_threshold_bytes: Optional[int] = None,
    process_set=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer with distributed gradient reduction.

    Matches the reference's knob set: ``op``, ``compression``,
    ``backward_passes_per_step`` (local aggregation: the collective fires
    every N-th update; in between, updates are zero and the inner
    optimizer state is untouched, like the reference's skipped
    synchronize), ``gradient_predivide_factor`` (splits the averaging
    divisor across pre/post scaling exactly as horovod/torch/optimizer.py
    does).
    """
    rop = normalize_op(op, average)
    pre, post = prescale_factor, postscale_factor
    if gradient_predivide_factor != 1.0:
        if rop != ReduceOp.AVERAGE:
            raise ValueError(
                "gradient_predivide_factor requires op=Average"
            )
        # Reference semantics: divide by predivide before the sum and by
        # (size / predivide) after; we fold the first into prescale and
        # let the Average op handle 1/size, compensating in postscale.
        pre = pre / gradient_predivide_factor
        post = post * gradient_predivide_factor

    def reduce_tree(grads):
        return allreduce_gradients(
            grads,
            axis_name=axis_name,
            op=rop,
            compression=compression,
            prescale_factor=pre,
            postscale_factor=post,
            fusion_threshold_bytes=fusion_threshold_bytes,
            process_set=process_set,
        )

    nonfinite = _nonfinite_action()

    def guarded_update(reduced, inner_state, params, extra):
        """Run the wrapped optimizer under the coordinated non-finite
        guard: the verdict is computed on the REDUCED gradients (the
        allreduce already propagated any rank's NaN/inf to every
        rank), so all ranks skip/zero/abort the step together."""
        if nonfinite == "off":
            return optimizer.update(reduced, inner_state, params, **extra)
        if axis_name is None:
            # Eager path: concrete arrays, Python control flow.
            if not bool(_tree_finite(reduced)):
                _M_NONFINITE.inc()
                if nonfinite == "abort":
                    raise HorovodInternalError(
                        "non-finite reduced gradients; aborting the "
                        "step on every rank "
                        "(HVTPU_NONFINITE_ACTION=abort)")
                if nonfinite == "skip":
                    return (
                        jax.tree_util.tree_map(jnp.zeros_like, reduced),
                        inner_state,
                    )
                reduced = _zero_nonfinite(reduced)
            return optimizer.update(reduced, inner_state, params, **extra)
        # In-jit the flag is traced: skip rides lax.cond.  abort cannot
        # raise from compiled code and degrades to a coordinated skip,
        # and the counter only advances on the eager path — both
        # documented in docs/robustness.md.
        if nonfinite == "zero":
            return optimizer.update(
                _zero_nonfinite(reduced), inner_state, params, **extra)
        finite = _tree_finite(reduced)

        def _apply(_):
            return optimizer.update(reduced, inner_state, params, **extra)

        def _skip(_):
            return (jax.tree_util.tree_map(jnp.zeros_like, reduced),
                    inner_state)

        return jax.lax.cond(finite, _apply, _skip, None)

    if backward_passes_per_step == 1:

        def init_fn(params):
            return optimizer.init(params)

        def update_fn(grads, state, params=None, **extra):
            reduced = reduce_tree(grads)
            return guarded_update(reduced, state, params, extra)

        return optax.GradientTransformation(init_fn, update_fn)

    n_acc = backward_passes_per_step

    def init_fn(params):
        return _DistOptState(
            inner=optimizer.init(params),
            acc=jax.tree_util.tree_map(jnp.zeros_like, params),
            step_in_cycle=jnp.zeros((), jnp.int32),
        )

    def update_fn(grads, state, params=None, **extra):
        acc = jax.tree_util.tree_map(jnp.add, state.acc, grads)
        count = state.step_in_cycle + 1

        def at_boundary(_):
            g = acc
            if average_aggregated_gradients:
                g = jax.tree_util.tree_map(lambda t: t / n_acc, g)
            reduced = reduce_tree(g)
            # Guarded: a skipped boundary still clears the accumulator
            # (the poisoned aggregation is discarded identically on
            # every rank; the inner state stays untouched).
            upd, inner = guarded_update(reduced, state.inner, params, extra)
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return upd, _DistOptState(inner, zeroed, jnp.zeros((), jnp.int32))

        def mid_cycle(_):
            upd = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return upd, _DistOptState(state.inner, acc, count)

        if axis_name is None:
            # Eager path: Python control flow on a concrete counter.
            if int(count) == n_acc:
                return at_boundary(None)
            # local aggregation only — no collective fired this call
            # (parity: the reference's skipped synchronize)
            obs_metrics.counter(
                "hvtpu_optimizer_skipped_steps_total",
                "Updates that only accumulated locally "
                "(backward_passes_per_step aggregation).",
            ).inc()
            return mid_cycle(None)
        # In-jit: the boundary test must be static-friendly; the cycle
        # counter is a traced value, so use lax.cond.  Collectives
        # execute unconditionally inside at_boundary's branch — XLA
        # requires both branches to be collective-free or the predicate
        # to be replicated; it is (same counter on every device).
        return jax.lax.cond(count == n_acc, at_boundary, mid_cycle, None)

    return optax.GradientTransformation(init_fn, update_fn)
