"""Environment-variable configuration, mirroring the reference's 3-layer
config scheme (env vars as source of truth; launcher flags mirror them;
see SURVEY.md §5.6).

Every knob reads ``HVTPU_<NAME>`` first and falls back to the reference's
``HOROVOD_<NAME>`` spelling so existing Horovod launch scripts keep working
(reference: horovod/common/operations.cc env parsing in
``InitializeHorovodOnce``; horovod/runner/launch.py flag->env mirroring).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(name: str, default=None):
    """HVTPU_x, falling back to HOROVOD_x, falling back to default."""
    for prefix in ("HVTPU_", "HOROVOD_"):
        v = os.environ.get(prefix + name)
        if v is not None:
            return v
    return default


def _env_int(name: str, default: int) -> int:
    v = _env(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = _env(name)
    return float(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool = False) -> bool:
    v = _env(name)
    if v in (None, ""):
        return default
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def _env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    v = _env(name)
    return v if v not in (None, "") else default


@dataclasses.dataclass
class Config:
    """Runtime configuration snapshot, read once at ``init()``.

    Field-by-field parity with the reference env namespace
    (HOROVOD_FUSION_THRESHOLD, HOROVOD_CYCLE_TIME, HOROVOD_CACHE_CAPACITY,
    HOROVOD_STALL_CHECK_*, HOROVOD_TIMELINE*, HOROVOD_AUTOTUNE*,
    HOROVOD_ELASTIC_*, HOROVOD_RANK/SIZE/... — SURVEY.md §5.6).
    """

    # --- fusion / cycle (FusionBufferManager + BackgroundThreadLoop knobs) ---
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    batch_d2d_memcopies: bool = True

    # --- wire format / reduction ---
    # "none" | "fp16" | "bf16" | "int8"  (int8 = EQuARX-style quantized wire)
    compression: str = "none"
    adasum: bool = False
    # two-stage eager allreduce over the (dcn, ici) process grid
    # (parity: HOROVOD_HIERARCHICAL_ALLREDUCE / NCCLHierarchicalAllreduce)
    hierarchical_allreduce: bool = False
    # multi-lane eager allreduce across a process's local devices
    # (snapshotted at init so a mid-run env flip cannot make one
    # process compile a different collective program than its peers)
    eager_multidevice: bool = True
    # set by the launcher when every host has the SAME slot count (0 =
    # non-uniform or unknown); hierarchical collectives require it so
    # all ranks agree on the (dcn, ici) grid
    uniform_local_size: int = 0

    # --- timeline / tracing ---
    timeline_filename: Optional[str] = None
    timeline_mark_cycles: bool = False
    # directory for per-rank cross-rank trace files (obs/tracing.py);
    # None disables tracing entirely (the hot-path guard is a single
    # module-attribute check)
    trace_dir: Optional[str] = None
    # KV clock-sync pings per rank at trace install (min-RTT sample
    # wins; more pings tighten the offset error bound)
    trace_clock_pings: int = 8

    # --- stall inspector ---
    stall_check_disable: bool = False
    stall_check_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0  # 0 = never abort
    # "amortized" (default: local bookkeeping + background heartbeat,
    # ~zero per-op cost, detection within one heartbeat) | "strict"
    # (pre-dispatch KV rendezvous per op: nothing dispatches until all
    # members confirm the same descriptor, at one KV round-trip per op)
    stall_check_mode: str = "amortized"
    stall_heartbeat_seconds: float = 0.5

    # --- autotune ---
    autotune: bool = False
    autotune_log: Optional[str] = None
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    # samples the GP (Bayesian) tuner takes before pinning the best
    autotune_gp_samples: int = 12
    # "gp" (Bayesian, reference parity) | "grid" (deterministic sweep)
    autotune_mode: str = "gp"

    # --- logging ---
    log_level: str = "warning"

    # --- process topology (set by the launcher, like HOROVOD_RANK/SIZE) ---
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    # --- coordination service (replaces the Gloo HTTP rendezvous KV) ---
    coordinator_addr: Optional[str] = None
    coordinator_port: int = 0
    # startup/rendezvous window (parity: horovodrun --start-timeout)
    start_timeout: float = 600.0

    # --- controller (eager mini-controller) transport ---
    controller_addr: Optional[str] = None
    controller_port: int = 0

    # --- elastic ---
    elastic: bool = False
    elastic_timeout: float = 600.0
    elastic_discovery_interval: float = 1.0
    # restart budget: total relaunches the elastic driver may perform
    # before declaring the workload crash-looping (-1 = unlimited);
    # with restart_window_seconds > 0 the budget applies to a sliding
    # window instead of the whole job
    max_restarts: int = -1
    restart_window_seconds: float = 0.0
    # blacklist cooldown (seconds): first strike sidelines a host for
    # this long, doubling per strike (exponential re-admission) up to
    # blacklist_cooldown_max_seconds
    blacklist_cooldown_seconds: float = 300.0
    blacklist_cooldown_max_seconds: float = 3600.0

    # --- graceful preemption / drain (core/preempt.py) ---
    # signal interpreted as a preemption notice ("" disables the
    # signal channel; the notice file and fault action still work)
    preempt_signal: str = "SIGTERM"
    # optional path polled for a preemption notice (file-based
    # platforms: metadata probes, node-problem-detector touch files)
    preempt_notice_file: Optional[str] = None
    # seconds a preempted worker may spend reaching a drain commit
    # before force-exiting with the planned-departure code anyway
    drain_grace_seconds: float = 30.0

    # --- fault injection (core/faults.py; docs/robustness.md) ---
    fault_spec: Optional[str] = None
    fault_seed: int = 0

    # --- CPU-simulation mode (localhost-as-cluster testing; set by
    # ``hvtpurun --cpu-devices N``): force the CPU platform with N XLA
    # devices in this process before the backend is touched. ---
    cpu_devices: int = 0

    @staticmethod
    def from_env() -> "Config":
        fusion_mb = _env_str("FUSION_THRESHOLD_MB")
        if fusion_mb is not None:
            fusion_bytes = int(float(fusion_mb) * 1024 * 1024)
        else:
            fusion_bytes = _env_int("FUSION_THRESHOLD", 64 * 1024 * 1024)
        return Config(
            fusion_threshold_bytes=fusion_bytes,
            cycle_time_ms=_env_float("CYCLE_TIME", 1.0),
            cache_capacity=_env_int("CACHE_CAPACITY", 1024),
            batch_d2d_memcopies=_env_bool("BATCH_D2D_MEMCOPIES", True),
            compression=_env_str("COMPRESSION", "none"),
            adasum=_env_bool("ADASUM", False),
            hierarchical_allreduce=_env_bool("HIERARCHICAL_ALLREDUCE",
                                             False),
            eager_multidevice=_env_bool("EAGER_MULTIDEVICE", True),
            uniform_local_size=_env_int("UNIFORM_LOCAL_SIZE", 0),
            timeline_filename=_env_str("TIMELINE"),
            timeline_mark_cycles=_env_bool("TIMELINE_MARK_CYCLES", False),
            trace_dir=_env_str("TRACE"),
            trace_clock_pings=_env_int("TRACE_CLOCK_PINGS", 8),
            stall_check_disable=_env_bool("STALL_CHECK_DISABLE", False),
            stall_check_time_seconds=_env_float("STALL_CHECK_TIME_SECONDS", 60.0),
            stall_shutdown_time_seconds=_env_float(
                "STALL_SHUTDOWN_TIME_SECONDS", 0.0
            ),
            stall_check_mode=_env_str("STALL_CHECK_MODE", "amortized"),
            stall_heartbeat_seconds=_env_float(
                "STALL_HEARTBEAT_SECONDS", 0.5
            ),
            autotune=_env_bool("AUTOTUNE", False),
            autotune_log=_env_str("AUTOTUNE_LOG"),
            autotune_warmup_samples=_env_int("AUTOTUNE_WARMUP_SAMPLES", 3),
            autotune_steps_per_sample=_env_int("AUTOTUNE_STEPS_PER_SAMPLE", 10),
            autotune_gp_samples=_env_int("AUTOTUNE_GP_SAMPLES", 12),
            autotune_mode=_env_str("AUTOTUNE_MODE", "gp"),
            log_level=_env_str("LOG_LEVEL", "warning"),
            rank=_env_int("RANK", 0),
            size=_env_int("SIZE", 1),
            local_rank=_env_int("LOCAL_RANK", 0),
            local_size=_env_int("LOCAL_SIZE", 1),
            cross_rank=_env_int("CROSS_RANK", 0),
            cross_size=_env_int("CROSS_SIZE", 1),
            coordinator_addr=_env_str("COORDINATOR_ADDR"),
            coordinator_port=_env_int("COORDINATOR_PORT", 0),
            start_timeout=_env_float("START_TIMEOUT", 600.0),
            controller_addr=_env_str("CONTROLLER_ADDR"),
            controller_port=_env_int("CONTROLLER_PORT", 0),
            elastic=_env_bool("ELASTIC", False),
            elastic_timeout=_env_float("ELASTIC_TIMEOUT", 600.0),
            elastic_discovery_interval=_env_float(
                "ELASTIC_DISCOVERY_INTERVAL", 1.0
            ),
            max_restarts=_env_int("MAX_RESTARTS", -1),
            restart_window_seconds=_env_float(
                "RESTART_WINDOW_SECONDS", 0.0
            ),
            blacklist_cooldown_seconds=_env_float(
                "BLACKLIST_COOLDOWN_SECONDS", 300.0
            ),
            blacklist_cooldown_max_seconds=_env_float(
                "BLACKLIST_COOLDOWN_MAX_SECONDS", 3600.0
            ),
            preempt_signal=_env_str("PREEMPT_SIGNAL", "SIGTERM"),
            preempt_notice_file=_env_str("PREEMPT_NOTICE_FILE"),
            drain_grace_seconds=_env_float("DRAIN_GRACE_SECONDS", 30.0),
            fault_spec=_env_str("FAULT_SPEC"),
            fault_seed=_env_int("FAULT_SEED", 0),
            cpu_devices=_env_int("CPU_DEVICES", 0),
        )
