"""Elastic training tests.

Unit layer (reference pattern: test/single/test_elastic_driver.py —
fake discovery scripts writing host lists to tmp files, no real
cluster): state commit/restore, discovery parsing, host manager
blacklist.  Integration layer lives in test_elastic_integration.py.
"""

import os

import pytest

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic
from horovod_tpu.elastic.discovery import HostDiscoveryScript, HostManager


class TestObjectState:
    def test_commit_restore_roundtrip(self, hvt):
        state = elastic.ObjectState(epoch=0, batch=0, items=[1, 2])
        state.epoch = 3
        state.batch = 7
        state.items.append(3)
        state.commit()
        state.epoch = 99
        state.items.append(99)
        state.restore()
        assert state.epoch == 3 and state.batch == 7
        assert state.items == [1, 2, 3]

    def test_restore_without_commit_returns_initial(self, hvt):
        state = elastic.ObjectState(epoch=5)
        state.epoch = 10
        state.restore()
        assert state.epoch == 5

    def test_reset_callbacks_fire_on_restore(self, hvt):
        state = elastic.ObjectState(epoch=0)
        fired = []
        state.register_reset_callbacks([lambda: fired.append(1)])
        state.commit()
        state.restore()
        assert fired == [1]

    def test_relaunch_generation_runs_reset_callbacks(
            self, hvt, monkeypatch):
        # A relaunched incarnation (driver sets
        # HVTPU_ELASTIC_GENERATION > 0) must run the user's reset
        # callbacks AFTER sync, so world-size-derived values (lr
        # schedules) are rebuilt instead of staying at the old
        # world's committed copy.
        events = []
        state = elastic.ObjectState(epoch=0)
        orig_sync = state.sync
        state.sync = lambda: (events.append("sync"), orig_sync())
        state.register_reset_callbacks(
            [lambda: events.append("reset_cb")])

        @elastic.run
        def train(st):
            events.append("train")

        monkeypatch.setenv("HVTPU_ELASTIC_GENERATION", "1")
        train(state)
        assert events == ["sync", "reset_cb", "train"]
        # first incarnation: no reset callbacks
        events.clear()
        monkeypatch.setenv("HVTPU_ELASTIC_GENERATION", "0")
        train(state)
        assert events == ["sync", "train"]

    def test_reset_callbacks_rebroadcast_after(self, hvt, monkeypatch):
        """ADVICE r5 ordering divergence: callbacks run after sync, so
        a rank-dependent callback could desync tracked state — the
        wrapper must re-broadcast tracked attributes afterwards."""
        events = []
        state = elastic.ObjectState(val=7)
        orig = state.rebroadcast
        state.rebroadcast = \
            lambda: (events.append("rebroadcast"), orig())[1]
        state.register_reset_callbacks([lambda: events.append("cb")])

        @elastic.run
        def train(st):
            events.append("train")

        monkeypatch.setenv("HVTPU_ELASTIC_GENERATION", "1")
        train(state)
        assert events == ["cb", "rebroadcast", "train"]
        # single-rank rebroadcast is an identity round-trip that also
        # refreshes the rollback snapshot
        assert state.val == 7 and state._saved == {"val": 7}

    def test_commit_persists_to_state_dir(self, hvt, tmp_path,
                                          monkeypatch):
        from horovod_tpu.core import durable as core_durable

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
        state = elastic.ObjectState(epoch=0)
        state.epoch = 4
        state.commit()
        state.wait_durable()
        # the commit landed as a manifest-verified snapshot under
        # commits/ (write-tmp → fsync → rename, manifest last)
        seq = core_durable.latest_verified(str(tmp_path))
        assert seq is not None
        # a fresh state syncs from the durable commit
        state2 = elastic.ObjectState(epoch=0)
        state2.sync()
        assert state2.epoch == 4

    def test_jax_state_roundtrips_arrays(self, hvt, tmp_path,
                                         monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
        params = {"w": jnp.arange(4.0), "b": jnp.zeros(2)}
        state = elastic.JaxState(params=params, epoch=1)
        state.commit()
        state.params = {"w": jnp.zeros(4), "b": jnp.ones(2)}
        state.restore()
        assert float(state.params["w"][3]) == 3.0
        fresh = elastic.JaxState(params={"w": jnp.zeros(4),
                                         "b": jnp.zeros(2)}, epoch=0)
        fresh.sync()
        assert fresh.epoch == 1
        assert float(fresh.params["w"][2]) == 2.0

    def test_commit_policy_throttles_durable_only(self, hvt, tmp_path,
                                                  monkeypatch):
        """set_commit_policy(every_n_commits=3): the durable file
        advances only on multiples, the in-memory rollback target on
        EVERY commit."""
        import pickle

        from horovod_tpu.core import durable as core_durable

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
        state = elastic.ObjectState(epoch=0)
        state.set_commit_policy(every_n_commits=3)

        def disk_epoch():
            state.wait_durable()
            seq = core_durable.latest_verified(str(tmp_path))
            if seq is None:
                return None
            payload = core_durable.read_snapshot(
                str(tmp_path), seq)["state.pkl"]
            return pickle.loads(payload)["epoch"]

        state.epoch = 1
        state.commit()   # count 1: memory only
        assert disk_epoch() is None
        # rollback still lands on the newest (memory) commit
        state.epoch = 99
        state.restore()
        assert state.epoch == 1
        state.epoch = 2
        state.commit()   # count 2: memory only
        assert disk_epoch() is None
        state.epoch = 3
        state.commit()   # count 3: durable
        assert disk_epoch() == 3
        state.epoch = 4
        state.commit()   # count 4: memory only — disk stays at 3
        assert disk_epoch() == 3
        # explicit save() is the unconditional escape hatch
        state.save()
        assert disk_epoch() == 4

    def test_commit_policy_validates(self, hvt):
        state = elastic.ObjectState(epoch=0)
        for bad in (0, 2.5, True):
            with pytest.raises(ValueError):
                state.set_commit_policy(every_n_commits=bad)

    def test_pending_resize_promotes_durable_commit(self, hvt, tmp_path,
                                                    monkeypatch):
        """A PLANNED resize must not lose throttled commits: with the
        host-update flag pending, the next commit() writes durably
        before raising HostsUpdatedInterrupt (rank-local states)."""
        import pickle

        from horovod_tpu.core import durable as core_durable
        from horovod_tpu.elastic.state import _HostUpdateFlag

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
        state = elastic.ObjectState(epoch=0)
        state.set_commit_policy(every_n_commits=10)
        state.epoch = 1
        state.commit()
        state.wait_durable()
        assert core_durable.latest_verified(str(tmp_path)) is None
        state.epoch = 2
        _HostUpdateFlag.instance().set()
        with pytest.raises(elastic.HostsUpdatedInterrupt):
            state.commit()
        state.wait_durable()
        seq = core_durable.latest_verified(str(tmp_path))
        assert seq is not None
        payload = core_durable.read_snapshot(
            str(tmp_path), seq)["state.pkl"]
        assert pickle.loads(payload)["epoch"] == 2

    def test_host_update_flag_raises_at_commit(self, hvt):
        from horovod_tpu.elastic.state import _HostUpdateFlag

        state = elastic.ObjectState(epoch=0)
        _HostUpdateFlag.instance().set()
        with pytest.raises(elastic.HostsUpdatedInterrupt):
            state.commit()
        # flag consumed: next commit is clean
        state.commit()


class TestTorchState:
    def test_model_optimizer_roundtrip(self, hvt):
        import torch

        from horovod_tpu.torch.elastic import TorchState

        model = torch.nn.Linear(3, 2)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        state = TorchState(model=model, optimizer=opt, epoch=0)
        w0 = model.weight.detach().clone()
        state.commit()
        with torch.no_grad():
            model.weight += 1.0
        state.epoch = 9
        state.restore()
        assert torch.allclose(model.weight, w0)
        assert state.epoch == 0

    def test_run_decorator_reexported(self, hvt):
        # parity: `import horovod.torch as hvd; hvd.elastic.run(...)`
        import horovod_tpu.torch as hvd_torch

        assert hvd_torch.elastic.run is elastic.run

    def test_elastic_sampler_reshards_and_skips(self, hvt):
        from horovod_tpu.torch.elastic import ElasticSampler

        data = list(range(20))
        s = ElasticSampler(data, shuffle=False)
        assert len(s) == 20  # world size 1
        s.record_batch(0, 4)
        sd = s.state_dict()
        s2 = ElasticSampler(data, shuffle=False)
        s2.load_state_dict(sd)
        assert len(s2) == 16
        assert set(iter(s2)).isdisjoint(set(range(4)))


class TestDiscovery:
    def _script(self, tmp_path, content):
        p = tmp_path / "discover.sh"
        p.write_text(f"#!/bin/sh\n{content}\n")
        p.chmod(0o755)
        return str(p)

    def test_parse_hosts_and_slots(self, tmp_path):
        script = self._script(
            tmp_path, 'echo "hostA:2"; echo "hostB:3"; echo "hostA:1"'
        )
        d = HostDiscoveryScript(script)
        assert d.find_available_hosts_and_slots() == {
            "hostA": 3, "hostB": 3
        }

    def test_script_failure_raises(self, tmp_path):
        script = self._script(tmp_path, "echo boom >&2; exit 3")
        with pytest.raises(RuntimeError, match="boom"):
            HostDiscoveryScript(script).find_available_hosts_and_slots()

    def test_host_manager_diff_and_blacklist(self, tmp_path):
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("a:2\nb:2\n")
        script = self._script(tmp_path, f'cat "{hosts_file}"')
        mgr = HostManager(HostDiscoveryScript(script))
        assert mgr.refresh() is True  # {} -> {a,b}
        assert mgr.available_slots() == 4
        assert mgr.refresh() is False  # unchanged
        hosts_file.write_text("a:2\n")
        assert mgr.refresh() is True
        assert mgr.host_spec() == "a:2"
        mgr.blacklist_host("a")
        hosts_file.write_text("a:2\nb:1\n")
        assert mgr.refresh() is True
        assert mgr.available_slots() == 1  # a filtered out
        assert mgr.host_spec() == "b:1"


class TestCooldownBlacklist:
    """The cooldown blacklist (ISSUE-2): exponential re-admission
    replaces upstream's permanent blacklist, strikes decay on
    successful incarnations, and the driver's wait loop can reason
    about the soonest re-admission."""

    def _mgr(self, tmp_path, spec="a:2\nb:2", base=10.0):
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text(spec + "\n")
        script = tmp_path / "discover.sh"
        script.write_text(f'#!/bin/sh\ncat "{hosts_file}"\n')
        script.chmod(0o755)
        return HostManager(HostDiscoveryScript(str(script)),
                           cooldown_base_s=base)

    def test_cooldown_doubles_per_strike(self, tmp_path):
        mgr = self._mgr(tmp_path)
        assert mgr.blacklist_host("a", now=100.0) == 10.0
        assert mgr.blacklist_host("a", now=100.0) == 20.0
        assert mgr.blacklist_host("a", now=100.0) == 40.0
        assert mgr.strikes("a") == 3

    def test_cooldown_is_capped(self, tmp_path):
        mgr = self._mgr(tmp_path, base=10.0)
        mgr.cooldown_max_s = 25.0
        for _ in range(5):
            cd = mgr.blacklist_host("a", now=0.0)
        assert cd == 25.0

    def test_readmission_after_cooldown(self, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.blacklist_host("a", now=100.0)  # until 110
        assert mgr.refresh(now=105.0) is True
        assert mgr.host_spec() == "b:2"
        assert mgr.blacklisted_now(now=105.0) == ["a"]
        # cooldown expired: the host is probed again
        assert mgr.refresh(now=111.0) is True
        assert mgr.host_spec() == "a:2,b:2"
        assert mgr.blacklisted_now(now=111.0) == []

    def test_success_decays_strikes(self, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.blacklist_host("a", now=0.0)
        mgr.blacklist_host("a", now=0.0)
        mgr.record_success("a")
        assert mgr.strikes("a") == 1
        mgr.record_success("a")
        assert mgr.strikes("a") == 0
        assert mgr.blacklisted_now(now=0.0) == []
        mgr.record_success("a")  # decay below zero is a no-op
        assert mgr.strikes("a") == 0
        # the next strike starts over at the BASE cooldown
        assert mgr.blacklist_host("a", now=0.0) == 10.0

    def test_exhausted_and_next_readmission(self, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.refresh(now=100.0)
        assert mgr.exhausted(2, now=100.0) is False
        mgr.blacklist_host("a", now=100.0)   # until 110
        mgr.blacklist_host("b", now=100.0)   # until 110
        mgr.blacklist_host("b", now=100.0)   # until 120
        mgr.refresh(now=105.0)
        assert mgr.exhausted(2, now=105.0) is True
        assert mgr.next_readmission_s(now=105.0) == 5.0
        # one cooldown lapses: no longer exhausted
        assert mgr.exhausted(2, now=115.0) is False


class TestRestartBudget:
    def _driver(self, tmp_path, **kw):
        from horovod_tpu.elastic.driver import ElasticDriver

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\n")
        script.chmod(0o755)
        return ElasticDriver(
            command=["true"],
            discovery=HostDiscoveryScript(str(script)),
            min_np=2, state_dir=str(tmp_path), **kw)

    def test_unlimited_by_default(self, tmp_path):
        d = self._driver(tmp_path)
        assert all(d._restart_budget_ok() for _ in range(50))

    def test_total_budget_trips(self, tmp_path, capsys):
        d = self._driver(tmp_path, max_restarts=2)
        assert d._restart_budget_ok() is True
        assert d._restart_budget_ok() is True
        assert d._restart_budget_ok() is False
        assert "restart budget exhausted" in capsys.readouterr().err

    def test_zero_budget_fails_on_first_restart(self, tmp_path, capsys):
        d = self._driver(tmp_path, max_restarts=0)
        d._last_crash_summary = "rank 1 on localhost exited 1"
        assert d._restart_budget_ok() is False
        err = capsys.readouterr().err
        assert "restart budget exhausted" in err
        assert "rank 1 on localhost exited 1" in err

    def test_window_forgives_old_restarts(self, tmp_path):
        d = self._driver(tmp_path, max_restarts=1,
                         restart_window=1000.0)
        assert d._restart_budget_ok() is True
        # age the recorded restart past the window: budget refills
        d._restart_times = [t - 2000.0 for t in d._restart_times]
        assert d._restart_budget_ok() is True
        assert d._restart_budget_ok() is False


class TestCoordinatorReelection:
    """Regression: when rank 0's HOST is struck out mid-job, the next
    incarnation's coordinator address must land on a SURVIVING host
    (driver._elect_coordinator — the seam _spawn routes through) and
    the hand-off must land in the flight ring as a
    ``coordinator_reelected`` event."""

    def _driver(self, tmp_path):
        from horovod_tpu.elastic.driver import ElasticDriver

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho hosta:2\necho hostb:2\n")
        script.chmod(0o755)
        return ElasticDriver(
            command=["true"],
            discovery=HostDiscoveryScript(str(script)),
            min_np=2, state_dir=str(tmp_path))

    def _slots(self, d, np):
        from horovod_tpu.runner import hosts as hosts_mod

        return hosts_mod.get_host_assignments(
            hosts_mod.parse_host_spec(d.hosts.host_spec()), np)

    def test_blacklisted_rank0_host_moves_coordinator(self, tmp_path):
        from horovod_tpu.obs import flight

        d = self._driver(tmp_path)
        d.hosts.refresh()
        d._generation += 1  # _spawn increments before electing
        assert d._elect_coordinator(self._slots(d, 4)) == "hosta"
        d._generation += 1
        flight.install(rank="driver", out_dir=str(tmp_path))
        try:
            # rank 0's host strikes out: host_spec() now excludes it,
            # so slots[0] — and the coordinator — moves to the survivor
            d.hosts.blacklist_host("hosta")
            assert d.hosts.refresh() is True
            assert d._elect_coordinator(self._slots(d, 2)) == "hostb"
            evs = [e for e in flight.get_recorder().events()
                   if e["kind"] == "coordinator_reelected"]
            assert len(evs) == 1
            assert evs[0]["old"] == "hosta"
            assert evs[0]["new"] == "hostb"
            assert evs[0]["generation"] == 1
        finally:
            flight.uninstall()

    def test_stable_coordinator_emits_no_event(self, tmp_path):
        from horovod_tpu.obs import flight

        d = self._driver(tmp_path)
        d.hosts.refresh()
        flight.install(rank="driver", out_dir=str(tmp_path))
        try:
            # same surviving slots[0] across a relaunch: no hand-off
            assert d._elect_coordinator(self._slots(d, 4)) == "hosta"
            assert d._elect_coordinator(self._slots(d, 4)) == "hosta"
            assert not [e for e in flight.get_recorder().events()
                        if e["kind"] == "coordinator_reelected"]
        finally:
            flight.uninstall()


class TestClockSeam:
    """Every blacklist/budget timing decision must route through the
    core/clock seam (not time.monotonic directly) so the fabric
    simulator can run the driver's control plane on virtual time.
    These tests install a fake clock on the test thread and advance it
    discretely — no real sleeps, no ``now=`` test-only overrides."""

    class _FakeClock:
        def __init__(self, t=0.0):
            self.t = t

        def monotonic(self):
            return self.t

        def wall(self):
            return self.t

        def sleep(self, seconds):
            self.t += seconds

        def call_later(self, delay_s, fn):  # pragma: no cover
            raise AssertionError("no timers expected in these paths")

    @pytest.fixture
    def fake_clock(self):
        from horovod_tpu.core import clock as core_clock

        fc = self._FakeClock()
        core_clock.install(fc)
        try:
            yield fc
        finally:
            core_clock.install(None)

    def _mgr(self, tmp_path, base=10.0):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho a:2\necho b:2\n")
        script.chmod(0o755)
        return HostManager(HostDiscoveryScript(str(script)),
                           cooldown_base_s=base)

    def test_cooldown_and_strike_decay_on_injected_clock(
            self, tmp_path, fake_clock):
        mgr = self._mgr(tmp_path, base=10.0)
        mgr.refresh()
        fake_clock.t = 100.0
        assert mgr.blacklist_host("a") == 10.0  # reads seam clock
        assert mgr.blacklisted_now() == ["a"]
        assert mgr.next_readmission_s() == pytest.approx(10.0)
        fake_clock.t = 105.0
        assert mgr.blacklisted_now() == ["a"]  # mid-cooldown
        assert mgr.next_readmission_s() == pytest.approx(5.0)
        assert mgr.refresh() is True  # cooling host drops out of the set
        fake_clock.t = 110.5
        assert mgr.blacklisted_now() == []  # cooldown expired
        assert mgr.refresh() is True  # host readmitted
        # strike survives readmission; decay is success-driven
        assert mgr.strikes("a") == 1
        mgr.record_success("a")
        assert mgr.strikes("a") == 0
        # a second strike after decay starts over at the base cooldown
        assert mgr.blacklist_host("a") == 10.0

    def test_exhausted_reads_injected_clock(self, tmp_path, fake_clock):
        mgr = self._mgr(tmp_path, base=10.0)
        mgr.refresh()
        fake_clock.t = 50.0
        mgr.blacklist_host("a")
        mgr.blacklist_host("b")
        assert mgr.exhausted(min_np=1) is True
        fake_clock.t = 60.5  # both cooldowns expired on the seam clock
        assert mgr.exhausted(min_np=1) is False

    def test_restart_budget_window_on_injected_clock(
            self, tmp_path, fake_clock, capsys):
        from horovod_tpu.elastic.driver import ElasticDriver

        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\n")
        script.chmod(0o755)
        d = ElasticDriver(
            command=["true"],
            discovery=HostDiscoveryScript(str(script)),
            min_np=2, state_dir=str(tmp_path),
            max_restarts=1, restart_window=60.0)
        fake_clock.t = 0.0
        assert d._restart_budget_ok() is True
        # the seam clock ages the first relaunch out of the window —
        # the budget refills with no mutation of driver internals
        fake_clock.t = 120.0
        assert d._restart_budget_ok() is True
        fake_clock.t = 121.0  # two relaunches inside one window: trip
        assert d._restart_budget_ok() is False
        assert "restart budget exhausted" in capsys.readouterr().err
