"""TF/Keras frontend across REAL processes (the `horovodrun -np 2
test_tensorflow.py` analog): cross-process gradient averaging through
DistributedGradientTape and a keras fit that stays in lockstep.
"""

import os

import pytest

import horovod_tpu
from horovod_tpu.runner import run

pytestmark = pytest.mark.multiprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


def test_tf_tape_and_collectives_2proc():
    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        out["sum"] = hvd.allreduce(
            tf.constant([float(r + 1)]), op=hvd.Sum
        ).numpy().tolist()
        out["gather"] = hvd.allgather(
            tf.fill((r + 1, 2), float(r))
        ).numpy().tolist()

        # tape averaging: rank-dependent grads -> identical average
        w = tf.Variable([[float(r + 1)]])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * float(10 * (r + 1)))
        dtape = hvd.DistributedGradientTape(tape)
        (g,) = dtape.gradient(loss, [w])
        out["tape_grad"] = g.numpy().ravel().tolist()

        v = tf.Variable([float(r * 100)])
        hvd.broadcast_variables([v], root_rank=1)
        out["bvar"] = v.numpy().tolist()
        return (r, out)

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    for r, out in results:
        assert out["sum"] == [3.0]
        assert out["gather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert out["tape_grad"] == [15.0]  # avg(10, 20)
        assert out["bvar"] == [100.0]


def test_tf_bare_collective_gradients_2proc():
    """Registered gradients (parity: RegisterGradient in
    horovod/tensorflow/mpi_ops.py): tape.gradient THROUGH a bare
    collective must equal the DistributedGradientTape result, and the
    allgather/broadcast adjoints must follow the reference rules."""

    def body():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        # grad of allreduce == allreduce of grad: a replicated weight
        # used through a bare averaged allreduce, with a RANK-LOCAL
        # loss on top, must produce the same gradient as
        # DistributedGradientTape over the equivalent local loss —
        # the backward allreduce averages the rank-dependent upstream
        # grads exactly like the tape wrapper averages local grads.
        w = tf.Variable([[2.0]])  # replicated start
        c = float(10 * (r + 1))  # rank-local coefficient
        with tf.GradientTape() as tape:
            red = hvd.allreduce(w, op=hvd.Average)
            loss = tf.reduce_sum(red * c)
        (g_bare,) = tape.gradient(loss, [w])
        out["bare"] = g_bare.numpy().ravel().tolist()

        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * c)
        dtape = hvd.DistributedGradientTape(tape)
        (g_dt,) = dtape.gradient(loss, [w])
        out["dtape"] = g_dt.numpy().ravel().tolist()

        # allgather grad: summed upstream grad, sliced to this rank's
        # rows — rank r contributed r+1 rows
        x = tf.Variable(tf.fill((r + 1, 2), 1.0))
        with tf.GradientTape() as tape:
            gathered = hvd.allgather(x)  # (3, 2)
            coeff = tf.constant([[1.0], [2.0], [3.0]])
            loss = tf.reduce_sum(gathered * coeff)
        (g,) = tape.gradient(loss, [x])
        out["gather_grad"] = g.numpy().tolist()

        # broadcast grad: reduce-to-root — root sums all ranks' grads,
        # non-roots get zeros
        b = tf.Variable([float(r + 5)])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(
                hvd.broadcast(b, root_rank=0) * float(r + 1))
        (g,) = tape.gradient(loss, [b])
        out["bcast_grad"] = g.numpy().tolist()
        return (r, out)

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    for r, out in results:
        # both paths average the per-rank coefficients: avg(10, 20)
        assert out["bare"] == out["dtape"] == [15.0]
        # upstream grads (the coeffs, identical on both ranks) are
        # SUMMED across ranks — global loss = sum of per-rank losses —
        # then sliced: rank 0 owned row 0 (coeff 1), rank 1 rows 1-2
        if r == 0:
            assert out["gather_grad"] == [[2.0, 2.0]]
        else:
            assert out["gather_grad"] == [[4.0, 4.0], [6.0, 6.0]]
        # broadcast grad: sum of per-rank upstream coeffs (1+2)=3 at
        # root, zero elsewhere
        assert out["bcast_grad"] == ([3.0] if r == 0 else [0.0])


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_keras_fit_lockstep_2proc():
    def body():
        import numpy as np

        import keras

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        rng = np.random.RandomState(r)  # DIFFERENT data per rank
        x = rng.rand(64, 4).astype(np.float32)
        y = x @ np.arange(4, dtype=np.float32).reshape(4, 1)

        keras.utils.set_random_seed(100 + r)  # different init per rank
        model = keras.Sequential([keras.layers.Dense(1)])
        dopt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.05)
        )
        model.compile(optimizer=dopt, loss="mse")
        model.fit(
            x, y, epochs=2, batch_size=16, verbose=0,
            callbacks=[
                hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                hvd.callbacks.MetricAverageCallback(),
            ],
        )
        return (r, [w.tolist() for w in model.get_weights()])

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    (r0, w0), (r1, w1) = results
    # broadcast + averaged grads keep ranks bit-identical despite
    # different data and different seeds
    assert w0 == w1


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_process_set_scoped_collectives_4proc():
    """Process-set scoping through the TF frontend (parity: the
    reference's TF ops all take process_set; torch coverage existed,
    TF had none): even/odd subsets run INDEPENDENT sync collectives
    and gradient averaging scoped to their set."""

    def body():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        assert hvd.size() == 4
        evens = hvd.add_process_set([0, 2])
        odds = hvd.add_process_set([1, 3])
        mine = evens if r % 2 == 0 else odds
        out = {}

        out["ar"] = hvd.allreduce(
            tf.constant([float(r)]), op=hvd.Sum,
            process_set=mine).numpy().tolist()
        out["gather"] = hvd.allgather(
            tf.constant([[float(r)]]),
            process_set=mine).numpy().ravel().tolist()
        out["bcast"] = hvd.broadcast(
            tf.constant([float(r)]), root_rank=mine.ranks[1],
            process_set=mine).numpy().tolist()
        # set-scoped gradient path: DistributedGradientTape averages
        # within the set only
        w = tf.Variable([1.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * float(r + 1))
        dtape = hvd.DistributedGradientTape(tape, process_set=mine)
        (g,) = dtape.gradient(loss, [w])
        out["tape"] = g.numpy().tolist()
        # set-scoped object plumbing
        out["obj"] = hvd.allgather_object(("rank", r), process_set=mine)
        return (r, out)

    results = run(body, np=4, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    for r, out in results:
        peers = [q for q in range(4) if q % 2 == r % 2]
        assert out["ar"] == [float(sum(peers))]
        assert out["gather"] == [float(q) for q in peers]
        assert out["bcast"] == [float(peers[1])]
        # tape averages (r+1) over the set members
        assert out["tape"] == [sum(q + 1 for q in peers) / 2]
        assert out["obj"] == [("rank", q) for q in peers]


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_v1_graph_optimizer_minimize_2proc():
    """tf.compat.v1 graph-mode DistributedOptimizer end-to-end at P=2
    (parity: the reference's test_tensorflow v1 session training): a
    real minimize() loop in a Session, rank-dependent data, weights in
    lockstep, loss decreasing."""

    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        tf1 = tf.compat.v1
        tf1.disable_eager_execution()
        g = tf.Graph()
        with g.as_default():
            # rank-local linear regression shard of one global problem
            rng = np.random.RandomState(0)
            x_all = rng.rand(64, 3).astype(np.float32)
            w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
            y_all = x_all @ w_true
            x_np, y_np = x_all[r::2], y_all[r::2]

            x = tf1.placeholder(tf.float32, [None, 3])
            y = tf1.placeholder(tf.float32, [None, 1])
            w = tf1.get_variable("w", initializer=tf.zeros([3, 1]))
            loss = tf1.reduce_mean(tf.square(x @ w - y))
            opt = hvd.DistributedOptimizer(
                tf1.train.GradientDescentOptimizer(0.5))
            train_op = opt.minimize(loss)
            bcast = [tf1.assign(w, hvd.broadcast(w, root_rank=0))]
            init = tf1.global_variables_initializer()

            with tf1.Session(graph=g) as sess:
                sess.run(init)
                sess.run(bcast)
                first = None
                for _ in range(40):
                    _, lv = sess.run(
                        [train_op, loss],
                        feed_dict={x: x_np, y: y_np})
                    if first is None:
                        first = lv
                final_w = sess.run(w)
        return (r, float(first), float(lv), final_w.ravel().tolist())

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    (r0, first0, last0, w0), (r1, first1, last1, w1) = results
    assert last0 < first0 * 0.2  # actually trained
    assert w0 == w1  # averaged gradients keep ranks in lockstep
    import numpy as np

    np.testing.assert_allclose(w0, [1.0, -2.0, 0.5], atol=0.15)


def test_sync_batch_normalization_2proc():
    """SyncBatchNormalization across real ranks: each rank holds half
    the global batch, and the layer's training output + moving stats
    must equal a single-process BatchNormalization over the FULL batch
    (parity: hvd.SyncBatchNormalization)."""
    import numpy as np

    def body():
        import keras
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        rng = np.random.RandomState(0)
        full = rng.rand(16, 4).astype(np.float32) * 2 + 3
        mine = full[r * 8:(r + 1) * 8]

        sbn = hvd.SyncBatchNormalization(momentum=0.9)
        with tf.GradientTape() as tape:
            y = sbn(tf.constant(mine), training=True)
            loss = tf.reduce_sum(tf.square(y))
        g_gamma, _ = tape.gradient(loss, sbn.trainable_variables)
        return (r, y.numpy().tolist(),
                sbn.moving_mean.numpy().tolist(),
                sbn.moving_variance.numpy().tolist(),
                g_gamma.numpy().tolist())

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    import keras

    rng = np.random.RandomState(0)
    full = rng.rand(16, 4).astype(np.float32) * 2 + 3
    bn = keras.layers.BatchNormalization(momentum=0.9)
    ref = bn(full, training=True).numpy()
    for r, y, mm, mv, gg in sorted(results):
        # per-rank output equals the full-batch BN's matching slice
        np.testing.assert_allclose(
            np.asarray(y), ref[r * 8:(r + 1) * 8],
            rtol=1e-4, atol=1e-4)
        # moving stats reflect GLOBAL batch statistics on every rank
        np.testing.assert_allclose(np.asarray(mm),
                                   bn.moving_mean.numpy(), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(mv),
                                   bn.moving_variance.numpy(),
                                   rtol=1e-4)
        assert all(np.isfinite(gg))


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_keras_load_model_lockstep_2proc(tmp_path):
    """hvd.load_model across real ranks: every rank loads the same
    checkpoint, refits on rank-dependent data, and the wrapped
    optimizer's gradient averaging keeps weights in lockstep."""
    import numpy as np

    save_dir = str(tmp_path)

    def body(save_dir):
        import keras
        import numpy as np

        import horovod_tpu.tensorflow.keras as hvd

        hvd.init()
        r = hvd.rank()
        path = save_dir + "/shared.keras"
        if r == 0:
            keras.utils.set_random_seed(0)
            m = keras.Sequential([
                keras.layers.Input((4,)), keras.layers.Dense(1)])
            m.compile(optimizer=keras.optimizers.Adam(0.05),
                      loss="mse")
            x0 = np.random.rand(32, 4).astype(np.float32)
            m.fit(x0, x0.sum(1, keepdims=True), epochs=1, verbose=0)
            m.save(path)
        hvd.allreduce(np.zeros(1), op=hvd.Sum)  # save barrier
        m = hvd.load_model(path)
        assert m.optimizer._hvtpu_distributed
        rng = np.random.RandomState(10 + r)  # rank-DEPENDENT data
        x = rng.rand(64, 4).astype(np.float32)
        y = x.sum(1, keepdims=True)
        m.fit(x, y, batch_size=16, epochs=1, verbose=0)
        return (r, [float(w.sum()) for w in m.get_weights()])

    results = run(body, args=(save_dir,), np=2, cpu_devices=1,
                  env=_ENV, start_timeout=300.0)
    (r0, w0), (r1, w1) = sorted(results)
    np.testing.assert_allclose(w0, w1, rtol=1e-5)


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_v1_broadcast_hook_monitored_session_2proc():
    """TF1 parity: BroadcastGlobalVariablesHook under a
    MonitoredTrainingSession equalizes rank-dependent initial
    variables to rank 0's values (the reference's canonical v1
    startup pattern)."""

    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        tf1 = tf.compat.v1
        tf1.disable_eager_execution()
        g = tf.Graph()
        with g.as_default():
            v1 = tf1.get_variable(
                "a", initializer=tf.fill([2, 2], float(10 + r)))
            v2 = tf1.get_variable(
                "b", initializer=tf.fill([3], float(100 + r)))
            hook = hvd.BroadcastGlobalVariablesHook(0)
            with tf1.train.MonitoredTrainingSession(
                    hooks=[hook]) as sess:
                a, b = sess.run([v1, v2])
        return (r, a.ravel().tolist(), b.tolist())

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    for r, a, b in results:
        assert a == [10.0] * 4  # rank 0's init, on both ranks
        assert b == [100.0] * 3


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_op_matrix_alltoall_reducescatter_sparse_2proc():
    """The remaining TF op matrix across real processes: variable-split
    alltoall, reducescatter (even + uneven), IndexedSlices allreduce,
    broadcast_object."""

    def body():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        splits = [1, 2] if r == 0 else [3, 1]
        t = tf.range(sum(splits), dtype=tf.float32) + 100.0 * r
        recv, rsplits = hvd.alltoall(t, splits=splits)
        out["a2a"] = recv.numpy().tolist()
        out["a2a_splits"] = rsplits.numpy().tolist()

        rs = hvd.reducescatter(tf.ones((4, 2)), op=hvd.Sum)
        out["rs"] = rs.numpy().tolist()
        rs_u = hvd.reducescatter(tf.ones((5, 2)), op=hvd.Sum)
        out["rs_uneven_rows"] = int(rs_u.shape[0])

        sl = tf.IndexedSlices(
            values=tf.constant([[float(r + 1)]]),
            indices=tf.constant([r]), dense_shape=tf.constant([2, 1]))
        red = hvd.allreduce(sl, op=hvd.Sum)
        out["slices_vals"] = red.values.numpy().ravel().tolist()
        out["slices_idx"] = red.indices.numpy().tolist()

        out["obj"] = hvd.broadcast_object(
            {"w": [1, 2, 3], "rank": r} if r == 0 else None,
            root_rank=0)
        return (r, out)

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    for r, out in results:
        # rank 0 receives: rank0's first 1 row + rank1's first 3 rows
        if r == 0:
            assert out["a2a"] == [0.0, 100.0, 101.0, 102.0]
            assert out["a2a_splits"] == [1, 3]
            assert out["rs_uneven_rows"] == 3
        else:
            assert out["a2a"] == [1.0, 2.0, 103.0]
            assert out["a2a_splits"] == [2, 1]
            assert out["rs_uneven_rows"] == 2
        assert out["rs"] == [[2.0, 2.0], [2.0, 2.0]]
        assert out["slices_vals"] == [1.0, 2.0]
        assert out["slices_idx"] == [0, 1]
        assert out["obj"] == {"w": [1, 2, 3], "rank": 0}


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_grouped_allgather_reducescatter_2proc():
    """TF grouped_allgather / grouped_reducescatter across real
    processes, values AND registered gradients (parity:
    hvd.grouped_allgather / hvd.grouped_reducescatter for TF)."""

    def body():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        # ragged dim-0 allgather as a group: rank r contributes r+1 rows
        xs = [tf.Variable(tf.fill((r + 1, 2), float(r))),
              tf.Variable([[10.0 + r]])]
        with tf.GradientTape() as tape:
            gathered = hvd.grouped_allgather(xs)
            coeff = tf.constant([[1.0], [2.0], [3.0]])
            loss = (tf.reduce_sum(gathered[0] * coeff)
                    + tf.reduce_sum(gathered[1] * 5.0))
        out["g0"] = gathered[0].numpy().tolist()
        out["g1"] = gathered[1].numpy().ravel().tolist()
        grads = tape.gradient(loss, xs)
        out["grad0"] = grads[0].numpy().tolist()
        out["grad1"] = grads[1].numpy().ravel().tolist()

        ys = [tf.Variable(tf.ones((4, 2))),
              tf.Variable([float(r + 1), 0.0])]
        with tf.GradientTape() as tape:
            red = hvd.grouped_reducescatter(ys, op=hvd.Sum)
            loss = (tf.reduce_sum(red[0] * 7.0)
                    + tf.reduce_sum(red[1] * 2.0))
        out["rs0"] = red[0].numpy().tolist()
        out["rs1"] = red[1].numpy().tolist()
        grads = tape.gradient(loss, ys)
        out["rsg0"] = grads[0].numpy().tolist()
        out["rsg1"] = grads[1].numpy().tolist()
        return (r, out)

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    for r, out in results:
        assert out["g0"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert out["g1"] == [10.0, 11.0]
        # upstream coeffs are summed across ranks then sliced to the
        # rows this rank contributed
        if r == 0:
            assert out["grad0"] == [[2.0, 2.0]]
        else:
            assert out["grad0"] == [[4.0, 4.0], [6.0, 6.0]]
        assert out["grad1"] == [10.0]
        # reducescatter: 4 rows over 2 ranks -> 2 rows each, summed
        assert out["rs0"] == [[2.0, 2.0], [2.0, 2.0]]
        # member 2: 2 elements over 2 ranks -> 1 each; sum = 1+2=3, 0
        assert out["rs1"] == ([3.0] if r == 0 else [0.0])
        # adjoint: allgather of the shard grads
        assert out["rsg0"] == [[7.0, 7.0]] * 4
        assert out["rsg1"] == [2.0, 2.0]


@pytest.mark.multiprocess
@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_alltoall_no_splits_ragged_grad_2proc():
    """Round-4 advisor finding: the no-splits alltoall gradient must
    replay with the NEGOTIATED received splits.  With ranks
    contributing different dim-0 row counts (legal: the engine only
    requires dim0 % size == 0), replaying with equal splits either
    crashes (received count not divisible) or routes gradient rows to
    the wrong senders."""

    def body():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        # rank 0 sends 4 rows (2 per peer), rank 1 sends 2 (1 per
        # peer): received counts are 3 and 3 — but NOT 2+2/1+1, so an
        # equal-splits replay would misroute or crash
        n = 4 if r == 0 else 2
        x = tf.range(float(n))
        with tf.GradientTape() as t:
            t.watch(x)
            out = hvd.alltoall(x)  # splits=None path
            # weight received rows by this rank's multiplier so the
            # gradient identifies which rank each sent row reached
            y = tf.reduce_sum(out * float(r + 1))
        g = t.gradient(y, x)
        return (r, int(out.shape[0]), g.numpy().tolist())

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    by_rank = dict((r, (n, g)) for r, n, g in results)
    # each rank receives 2 rows from rank 0 + 1 row from rank 1
    assert by_rank[0][0] == 3 and by_rank[1][0] == 3
    # rank 0's rows [0,1] went to rank 0 (x1), rows [2,3] to rank 1 (x2)
    assert by_rank[0][1] == [1.0, 1.0, 2.0, 2.0]
    # rank 1's row [0] went to rank 0 (x1), row [1] to rank 1 (x2)
    assert by_rank[1][1] == [1.0, 2.0]


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_tf_graph_mode_fused_broadcast_2proc():
    """Graph-mode (tf.function) broadcast_variables across real
    processes: the fused per-dtype path must deliver rank-0 values to
    every rank inside a traced function."""

    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        vs = [tf.Variable(tf.fill((4,), float((r + 1) * (i + 1))))
              for i in range(6)]
        iv = tf.Variable(tf.constant([r, r], tf.int32))

        @tf.function
        def sync():
            hvd.broadcast_variables(vs + [iv], root_rank=0)

        sync()
        # rank 0's values everywhere: (i+1) for the floats, [0, 0] int
        ok_f = all(
            np.allclose(v.numpy(), np.full((4,), float(i + 1)))
            for i, v in enumerate(vs)
        )
        ok_i = iv.numpy().tolist() == [0, 0]

        # graph-mode collective correctness too (allreduce in a trace)
        @tf.function
        def red():
            return hvd.allreduce(tf.constant([float(r + 1)]), op=hvd.Sum)

        s = float(red().numpy()[0])
        return (r, ok_f, ok_i, s)

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    for r, ok_f, ok_i, s in results:
        assert ok_f and ok_i
        assert s == 3.0
