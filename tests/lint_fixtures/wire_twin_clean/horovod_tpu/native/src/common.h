// Minimal fixture twin of native/src/common.h (wire-twin clean case).
#pragma once
#include <cstdint>

namespace hvt {

enum class DataType : uint8_t {
  kUint8 = 0,
  kFloat32 = 1,
};

enum class OpType : uint8_t {
  kAllreduce = 0,
  kBarrier = 1,
};

enum class RedOp : uint8_t {
  kSum = 0,
  kAverage = 1,
};

inline int64_t DataTypeSize(DataType t) {
  switch (t) {
    case DataType::kUint8:
      return 1;
    default:
      return 4;
  }
}

}  // namespace hvt
