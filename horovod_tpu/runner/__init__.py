"""Launcher / runner: ``hvtpurun`` CLI and the programmatic ``run()``.

Parity surface: ``horovod/runner/`` — ``horovodrun`` (launch.py),
``horovod.run()`` (``__init__.py``), host parsing, safe shell
execution, and the elastic driver (horovod_tpu.elastic.driver).
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

from .hosts import (  # noqa: F401
    HostSlots,
    SlotInfo,
    get_host_assignments,
    parse_host_spec,
)
from .launch import (  # noqa: F401
    build_worker_env,
    find_free_port,
    launch_workers,
    main,
    parse_args,
)


class RunError(RuntimeError):
    """A worker failed during ``run()``; carries the rank's traceback."""

    def __init__(self, rank: int, worker_traceback: str):
        super().__init__(
            f"rank {rank} failed:\n{worker_traceback}"
        )
        self.rank = rank
        self.worker_traceback = worker_traceback


def _dump_fn(fn: Callable, args, kwargs, path: str, key: str):
    """Pickle + HMAC-sign the function blob (parity: secret.py-signed
    service messages; workers refuse unsigned/tampered payloads)."""
    from . import secret

    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover - cloudpickle is available
        import pickle as pickler
    blob = pickler.dumps((fn, tuple(args), dict(kwargs or {})))
    with open(path, "wb") as f:
        f.write(secret.sign(key, blob))


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    np: int = 2,
    cpu_devices: Optional[int] = None,
    hosts: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = 600.0,
    start_timeout: Optional[float] = None,  # rendezvous window (env)
    extra_flags: Optional[List[str]] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` local worker processes and
    return the per-rank results, ordered by rank.

    Parity: ``horovod.run()`` (horovod/runner/__init__.py) — the
    function rides cloudpickle to each rank; each rank's return value is
    collected by the launcher.  ``cpu_devices`` forces the CPU platform
    with that many XLA devices per worker (the localhost-as-cluster test
    mode; SURVEY.md §4 pattern 2).  ``timeout`` is a hard deadline for
    the whole job (None = unlimited) — unlike ``hvtpurun``, the
    programmatic API defaults to bounded so test harnesses can't hang.
    ``start_timeout`` only bounds the workers' rendezvous window
    (parity: horovod.run's start_timeout), not job duration.
    """
    from . import launch as launch_mod
    from . import secret

    job_key = secret.make_secret_key()
    with tempfile.TemporaryDirectory(prefix="hvtpurun_") as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        out_dir = os.path.join(tmp, "results")
        os.makedirs(out_dir)
        _dump_fn(fn, args, kwargs, fn_path, job_key)
        argv = ["-np", str(np)]
        if cpu_devices is not None:
            argv += ["--cpu-devices", str(cpu_devices)]
        if verbose:
            argv += ["--verbose"]
        if start_timeout is not None:
            argv += ["--start-timeout", str(start_timeout)]
        argv += extra_flags or []
        argv += [
            sys.executable, "-m", "horovod_tpu.runner.run_task",
            fn_path, out_dir,
        ]
        ns = launch_mod.parse_args(argv)
        base_env = dict(os.environ)
        base_env.update(env or {})
        # key travels by 0600 file, not env value: the ssh path
        # serializes the worker env into world-readable argv (the
        # fn/result channel already requires a shared filesystem, so
        # the key file rides the same one)
        key_path = os.path.join(tmp, "job.key")
        secret.write_key_file(job_key, key_path)
        base_env[secret.ENV_KEY_FILE] = key_path
        base_env.pop(secret.ENV_KEY, None)
        # hosts: e.g. "localhost:2,127.0.0.1:2" to shape local/cross
        # topology while still spawning locally (both names are local)
        host_spec = hosts or f"localhost:{np}"
        slots = get_host_assignments(parse_host_spec(host_spec), np)
        port = launch_mod.find_free_port()
        code = launch_workers(
            ns.command,
            slots,
            "127.0.0.1",
            port,
            args=ns,
            base_env=base_env,
            job_timeout=timeout,
        )
        # Collect every rank's payload FIRST, then report the most
        # informative failure: a rank that wrote (ok=False, traceback)
        # beats 'no result file' from a peer the launcher terminated.
        payloads: Dict[int, tuple] = {}
        bad_signature: Dict[int, str] = {}
        for r in range(np):
            path = os.path.join(out_dir, f"rank_{r}.pkl")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    # verify the worker's signature before unpickling —
                    # result files cross the same trust boundary as the
                    # shipped function.  A bad signature on one rank must
                    # not abort collection of the rest: record it and keep
                    # going so the report carries every rank's status
                    # (the tampered blob is still never unpickled).
                    try:
                        blob = secret.verify(job_key, f.read())
                    except secret.SignatureError as e:
                        bad_signature[r] = str(e)
                        continue
                payloads[r] = pickle.loads(blob)
        def _others(r: int) -> str:
            return "Other ranks: " + ", ".join(
                f"rank {q}: "
                + ("failed" if q in payloads and not payloads[q][0] else
                   "ok" if q in payloads else
                   "bad signature" if q in bad_signature else
                   "no result file")
                for q in range(np) if q != r
            )

        for r in range(np):
            item = payloads.get(r)
            if item is not None and not item[0]:
                # a concurrent tampering signal must not be buried under
                # an ordinary worker crash — carry every rank's status
                raise RunError(r, item[1] + "\n" + _others(r))
        if bad_signature:
            r = min(bad_signature)
            raise RunError(
                r,
                f"result file failed signature verification "
                f"({bad_signature[r]}); the blob was not unpickled. "
                + _others(r),
            )
        for r in range(np):
            if r not in payloads:
                raise RunError(
                    r,
                    f"no result file (worker exit code {code}; it may "
                    "have crashed or been terminated before writing "
                    "results)",
                )
        if code != 0:
            raise RunError(-1, f"launcher observed exit code {code}")
        return [payloads[r][1] for r in range(np)]
