"""Torch-tensor collectives over the TPU engine (parity:
horovod/torch/mpi_ops.py + the C++ binding horovod/torch/mpi_ops_v2.cc).

Where the reference wraps ``at::Tensor`` into ``TorchTensor`` adapters
and enqueues into the C++ core, here the adapter boundary is
torch(CPU) ↔ numpy ↔ jax: zero-copy for contiguous CPU tensors in both
directions (``Tensor.numpy()`` / ``torch.from_numpy``).  Sync ops call
the engine directly; async ops flow through the eager mini-controller
(out-of-order enqueue tolerance, fusion, response cache) and return
integer handles compatible with ``synchronize``/``poll``.
"""

from __future__ import annotations

import warnings
from typing import List, Optional

import numpy as np
import torch

import horovod_tpu as _hvt

from .compression import Compression

# Re-exported reduce ops (parity: hvd.Sum/Average/Adasum/Min/Max/Product)
Sum = _hvt.Sum
Average = _hvt.Average
Adasum = _hvt.Adasum
Min = _hvt.Min
Max = _hvt.Max
Product = _hvt.Product


_TORCH_HANDLES = {}  # handle -> (payload for post-processing)


_warned_fp64 = False


def _to_np(tensor: torch.Tensor) -> np.ndarray:
    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.bfloat16:
        # numpy has no bf16; round-trip via fp32 (values preserved).
        return t.to(torch.float32).numpy()
    if t.dtype == torch.float64:
        import jax
        global _warned_fp64
        if not jax.config.jax_enable_x64 and not _warned_fp64:
            _warned_fp64 = True
            warnings.warn(
                "float64 tensor reduced without jax_enable_x64: the "
                "collective runs at float32 wire precision and the result "
                "is cast back to float64.  Set jax.config.update("
                "'jax_enable_x64', True) for true-fp64 collectives.",
                UserWarning, stacklevel=3,
            )
    return t.numpy()


def _from_np(arr, like: Optional[torch.Tensor] = None) -> torch.Tensor:
    a = np.ascontiguousarray(arr)
    if not a.flags.writeable:
        a = a.copy()  # jax buffers are read-only; torch wants writable
    out = torch.from_numpy(a)
    # Restore the caller's dtype: the engine computes in jax's dtype
    # system (fp64 math runs at fp32 wire precision unless
    # jax_enable_x64 is set; bf16 round-trips via fp32 since numpy has
    # no bf16).
    if like is not None and out.dtype != like.dtype:
        out = out.to(like.dtype)
    return out


def _engine_compression(compression):
    """Map torch-side Compression intent onto the engine's wire codec."""
    from ..comm.compression import Compression as EngineCompression

    if compression in (Compression.fp16,):
        return EngineCompression.fp16
    if compression in (Compression.bf16,):
        return EngineCompression.bf16
    return EngineCompression.none


# ---------------------------------------------------------------------------
# synchronous ops
# ---------------------------------------------------------------------------

def allreduce(tensor: torch.Tensor, average=None, name=None,
              compression=Compression.none, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None) -> torch.Tensor:
    """Averaged (by default) allreduce returning a NEW tensor (parity:
    hvd.allreduce in horovod/torch/mpi_ops.py)."""
    out = _hvt.allreduce(
        _to_np(tensor), op=op, average=average,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=_engine_compression(compression),
        process_set=process_set, name=name,
    )
    return _from_np(np.asarray(out), like=tensor).reshape(tensor.shape)


def allreduce_(tensor: torch.Tensor, average=None, name=None,
               compression=Compression.none, op=None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               process_set=None) -> torch.Tensor:
    """In-place allreduce (parity: hvd.allreduce_)."""
    result = allreduce(
        tensor, average=average, name=name, compression=compression, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    tensor.data.copy_(result)
    return tensor


def grouped_allreduce(tensors: List[torch.Tensor], average=None, name=None,
                      compression=Compression.none, op=None,
                      process_set=None) -> List[torch.Tensor]:
    outs = _hvt.grouped_allreduce(
        [_to_np(t) for t in tensors], op=op, average=average,
        compression=_engine_compression(compression),
        process_set=process_set,
    )
    return [
        _from_np(np.asarray(o), like=t).reshape(t.shape)
        for o, t in zip(outs, tensors)
    ]


def grouped_allreduce_(tensors: List[torch.Tensor], **kw) -> List[torch.Tensor]:
    outs = grouped_allreduce(tensors, **kw)
    for t, o in zip(tensors, outs):
        t.data.copy_(o)
    return tensors


def allgather(tensor: torch.Tensor, name=None, process_set=None
              ) -> torch.Tensor:
    """Concatenate along dim 0 across ranks (ragged dim-0 supported;
    parity: hvd.allgather / allgather size negotiation)."""
    out = _hvt.allgather(_to_np(tensor), process_set=process_set, name=name)
    return _from_np(np.asarray(out), like=tensor)


def broadcast(tensor: torch.Tensor, root_rank: int = 0, name=None,
              process_set=None) -> torch.Tensor:
    out = _hvt.broadcast(_to_np(tensor), root_rank=root_rank,
                         process_set=process_set, name=name)
    return _from_np(np.asarray(out), like=tensor).reshape(tensor.shape)


def broadcast_(tensor: torch.Tensor, root_rank: int = 0, name=None,
               process_set=None) -> torch.Tensor:
    tensor.data.copy_(broadcast(tensor, root_rank, name, process_set))
    return tensor


def alltoall(tensor: torch.Tensor, splits: Optional[torch.Tensor] = None,
             name=None, process_set=None):
    """Scatter dim-0 slices to every rank, gather received (parity:
    hvd.alltoall; returns (output, received_splits) like the reference
    when splits is given)."""
    splits_np = None if splits is None else _to_np(splits)
    out = _hvt.alltoall(_to_np(tensor), splits_np, process_set=process_set,
                        name=name)
    if isinstance(out, tuple):
        data, rsplits = out
        return (_from_np(np.asarray(data), like=tensor),
                torch.as_tensor(np.asarray(rsplits)))
    return _from_np(np.asarray(out), like=tensor)


def reducescatter(tensor: torch.Tensor, op=None, name=None,
                  process_set=None) -> torch.Tensor:
    out = _hvt.reducescatter(_to_np(tensor), op=op, process_set=process_set,
                             name=name)
    return _from_np(np.asarray(out), like=tensor)


def barrier(process_set=None):
    _hvt.barrier(process_set=process_set)


# ---------------------------------------------------------------------------
# async ops + handle management
# ---------------------------------------------------------------------------

def allreduce_async(tensor: torch.Tensor, average=None, name=None,
                    op=None, compression=Compression.none,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> int:
    handle = _hvt.allreduce_async(
        _to_np(tensor), op=op, average=average, name=name,
        compression=_engine_compression(compression),
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    _TORCH_HANDLES[handle] = ("new", tensor)
    return handle


def allreduce_async_(tensor: torch.Tensor, average=None, name=None,
                     op=None, compression=Compression.none,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set=None) -> int:
    """Async in-place allreduce: result lands in ``tensor`` at
    synchronize (parity: hvd.allreduce_async_)."""
    handle = _hvt.allreduce_async(
        _to_np(tensor), op=op, average=average, name=name,
        compression=_engine_compression(compression),
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    _TORCH_HANDLES[handle] = ("inplace", tensor)
    return handle


def grouped_allreduce_async(tensors: List[torch.Tensor], average=None,
                            names=None, op=None,
                            compression=Compression.none,
                            process_set=None) -> List[int]:
    handles = _hvt.grouped_allreduce_async(
        [_to_np(t) for t in tensors], op=op, average=average, names=names,
        compression=_engine_compression(compression),
        process_set=process_set,
    )
    for h, t in zip(handles, tensors):
        _TORCH_HANDLES[h] = ("new", t)
    return handles


def allgather_async(tensor: torch.Tensor, name=None, process_set=None) -> int:
    handle = _hvt.allgather_async(_to_np(tensor), name=name,
                                  process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


def broadcast_async(tensor: torch.Tensor, root_rank: int = 0, name=None,
                    process_set=None) -> int:
    handle = _hvt.broadcast_async(_to_np(tensor), root_rank=root_rank,
                                  name=name, process_set=process_set)
    _TORCH_HANDLES[handle] = ("new", tensor)
    return handle


def broadcast_async_(tensor: torch.Tensor, root_rank: int = 0, name=None,
                     process_set=None) -> int:
    handle = _hvt.broadcast_async(_to_np(tensor), root_rank=root_rank,
                                  name=name, process_set=process_set)
    _TORCH_HANDLES[handle] = ("inplace", tensor)
    return handle


def alltoall_async(tensor: torch.Tensor, splits=None, name=None,
                   process_set=None) -> int:
    splits_np = None if splits is None else _to_np(splits)
    handle = _hvt.alltoall_async(_to_np(tensor), splits_np, name=name,
                                 process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


def reducescatter_async(tensor: torch.Tensor, op=None, name=None,
                        process_set=None) -> int:
    handle = _hvt.reducescatter_async(_to_np(tensor), op=op, name=name,
                                      process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


def synchronize(handle: int):
    """Wait for an async op; returns the torch result (and applies the
    in-place semantics for *_async_ variants)."""
    mode, ref = _TORCH_HANDLES.pop(handle, ("new", None))
    out = _hvt.synchronize(handle)
    if isinstance(out, tuple):  # alltoall with splits
        data, rsplits = out
        return (_from_np(np.asarray(data), like=ref),
                torch.as_tensor(np.asarray(rsplits)))
    if out is None:  # barrier-like
        return None
    result = _from_np(np.asarray(out), like=ref)
    if mode == "inplace" and ref is not None:
        ref.data.copy_(result.reshape(ref.shape))
        return ref
    if mode == "new" and ref is not None:
        return result.reshape(ref.shape)
    return result


def poll(handle: int) -> bool:
    return _hvt.poll(handle)


def join(device=None) -> int:
    return _hvt.join(device)
