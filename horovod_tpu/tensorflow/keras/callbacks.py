"""Parity shim: ``horovod/tensorflow/keras/callbacks.py`` re-exports
the shared callback implementations (reference shares them via
``horovod/_keras/callbacks.py``)."""

from ...keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
