"""KerasEstimator — Spark-style estimator over the keras frontend.

Parity surface: ``horovod/spark/keras/estimator.py``
(``KerasEstimator``, ``KerasModel``) + ``.../keras/remote.py``: fit()
rebuilds the model on every rank from its architecture JSON + initial
weights, compiles it with the wrapped ``DistributedOptimizer`` and the
Horovod callbacks (broadcast at start, metric averaging), trains
``model.fit`` on the rank's shard, checkpoints through the Store, and
returns a KerasModel for transform().

TPU-native notes: the gradient fabric under the wrapped optimizer is
the JAX/XLA collective path of ``horovod_tpu.keras``; data is the
Store's materialized npz (common.data), not Petastorm.  The optimizer
ships as a keras config dict (not pickle) — its slot variables are
rank-local and must be built fresh against the rebuilt model.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

from ..common.data import TRAIN_NPZ, VAL_NPZ, load_shard
from ..common.estimator import (
    HorovodEstimator,
    HorovodModel,
    resolve_compression,
)

CHECKPOINT_FILE = "checkpoint.npz"
MODEL_JSON_FILE = "model.json"


def _keras_trainer(spec: Dict[str, Any]):
    """Per-rank training loop (reference: keras/remote.py) —
    module-level so the launcher channel pickles it by reference."""
    import cloudpickle
    import numpy as np

    import horovod_tpu.keras as hvd
    from ..common.store import FilesystemStore

    hvd.init()
    import keras

    p = spec["params"]
    seed = p.get("random_seed")
    if seed is not None:
        keras.utils.set_random_seed(seed + hvd.rank())

    model = keras.models.model_from_json(
        spec["model_json"], custom_objects=spec["custom_objects"])
    model.set_weights(cloudpickle.loads(spec["weights_blob"]))
    # Resume (parity: reference checkpoint-resume on refit): rank 0
    # loads the run's latest Store checkpoint over the shipped
    # weights; BroadcastGlobalVariablesCallback propagates them.
    if p.get("resume_from_checkpoint") and hvd.rank() == 0:
        import os as _os

        _ckpt = _os.path.join(
            FilesystemStore(spec["store_prefix"]).get_checkpoint_path(
                spec["run_id"]), CHECKPOINT_FILE)
        if _os.path.exists(_ckpt):
            with np.load(_ckpt) as z:
                model.set_weights(
                    [z[f"w{i}"] for i in range(len(z.files))])
    optimizer = keras.optimizers.deserialize(
        json.loads(spec["optimizer_config"]))
    loss, metrics, user_callbacks, transformation_fn = \
        cloudpickle.loads(spec["train_blob"])
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            optimizer,
            compression=resolve_compression(
                hvd, p.get("gradient_compression")
                or p.get("compression"))),
        loss=loss, metrics=metrics or None,
        loss_weights=p.get("loss_weights"),
        weighted_metrics=None,
    )

    store = FilesystemStore(spec["store_prefix"])
    run_id = spec["run_id"]
    shard = load_shard(store.get_train_data_path(), TRAIN_NPZ,
                       hvd.rank(), hvd.size())
    if len(next(iter(shard.values()))) == 0:
        raise ValueError(
            f"rank {hvd.rank()}'s training shard is empty "
            f"({spec['n_train']} rows over {hvd.size()} ranks); "
            "reduce num_proc or provide more data")
    # rank-CONSISTENT batch count: strided shards differ by up to one
    # row, which can flip ceil(rows/batch) on one rank — and every
    # training batch fires collective gradient allreduces, so unequal
    # counts deadlock the epoch. Trim to the global minimum (drops at
    # most one row per rank per epoch).
    min_rows = max(1, spec["n_train"] // hvd.size())
    shard = {c: v[:min_rows] for c, v in shard.items()}

    feature_cols = p["feature_cols"]
    label_cols = p["label_cols"]

    def xy(source):
        xs = [source[c] for c in feature_cols]
        ys = [source[c] for c in label_cols]
        x = xs[0] if len(xs) == 1 else xs
        y = ys[0] if len(ys) == 1 else ys
        if transformation_fn is not None:
            x, y = transformation_fn(x, y)
        return x, y

    x, y = xy(shard)
    fit_kwargs: Dict[str, Any] = {}
    # validation engages only when EVERY rank's strided shard is
    # non-empty (rows[r::size] nonempty iff r < n_val) — a per-rank
    # skip would desync the metric-averaging collectives, and an empty
    # shard would crash keras mid-fit while peers sit in a collective
    if 0 < spec["n_val"] < hvd.size() and hvd.rank() == 0:
        import logging

        logging.getLogger("horovod_tpu").warning(
            "validation disabled: %d validation rows cannot cover %d "
            "ranks (every rank needs >=1 row or the metric collectives "
            "desync); grow the validation split or reduce num_proc",
            spec["n_val"], hvd.size())
    if spec["n_val"] >= hvd.size():
        vshard = load_shard(store.get_val_data_path(), VAL_NPZ,
                            hvd.rank(), hvd.size())
        vx, vy = xy(vshard)
        if p.get("sample_weight_col"):
            # weighted val_loss, matching the torch trainer's
            # weighted validation for the same param
            fit_kwargs["validation_data"] = (
                vx, vy, vshard[p["sample_weight_col"]])
        else:
            fit_kwargs["validation_data"] = (vx, vy)
        if p.get("validation_steps_per_epoch") is not None:
            fit_kwargs["validation_steps"] = \
                p["validation_steps_per_epoch"]
    if p.get("sample_weight_col"):
        fit_kwargs["sample_weight"] = shard[p["sample_weight_col"]]
    if p.get("train_steps_per_epoch") is not None:
        fit_kwargs["steps_per_epoch"] = p["train_steps_per_epoch"]

    ckpt_dir = store.get_checkpoint_path(run_id)

    class _Checkpoint(keras.callbacks.Callback):
        """rank-0 per-epoch Store checkpoint (reference: the estimator
        installs a best-model checkpoint callback writing to the
        Store)."""

        def on_epoch_end(self, epoch, logs=None):
            if hvd.rank() != 0:
                return
            os.makedirs(ckpt_dir, exist_ok=True)
            tmp = os.path.join(ckpt_dir, CHECKPOINT_FILE + ".tmp.npz")
            np.savez(tmp, **{f"w{i}": w for i, w in
                             enumerate(self.model.get_weights())})
            os.replace(tmp, os.path.join(ckpt_dir, CHECKPOINT_FILE))

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        _Checkpoint(),
    ] + list(user_callbacks or [])

    hist = model.fit(
        x, y,
        batch_size=p["batch_size"],
        epochs=p["epochs"],
        shuffle=p.get("shuffle", True),
        verbose=p.get("verbose", 1) if hvd.rank() == 0 else 0,
        callbacks=callbacks,
        **fit_kwargs,
    )

    history = {k: [float(v) for v in vs] for k, vs in
               hist.history.items()}
    result: Dict[str, Any] = {"history": history}
    if hvd.rank() == 0:
        store.write_text(
            os.path.join(store.get_logs_path(run_id), "history.json"),
            json.dumps(history))
        store.write_text(
            os.path.join(ckpt_dir, MODEL_JSON_FILE), spec["model_json"])
        result["weights_blob"] = cloudpickle.dumps(model.get_weights())
    hvd.shutdown()
    return result


class KerasEstimator(HorovodEstimator):
    """Reference-shaped params: ``model`` (keras.Model), ``optimizer``
    (keras optimizer instance or name), ``loss`` (name or callable),
    ``custom_objects`` for model rebuild on the ranks."""

    _param_defs = {
        "optimizer": None,
        "custom_objects": {},
    }

    def _check_params(self):
        super()._check_params()
        if self.getOptimizer() is None:
            raise ValueError("optimizer param is required")
        if self.getLoss() is None:
            raise ValueError("loss param is required")
        lw = self.getLossWeights()
        if lw is not None and len(lw) != len(self.getLabelCols() or []):
            raise ValueError(
                f"loss_weights has {len(lw)} entries for "
                f"{len(self.getLabelCols() or [])} output column(s)")

    def _serialize_training_spec(self) -> Dict[str, Any]:
        import cloudpickle
        import keras

        model = self.getModel()
        if not model.built:
            raise ValueError(
                "the keras model must be built before fit() so its "
                "initial weights can broadcast — call model.build() "
                "or pass an Input layer")
        opt = self.getOptimizer()
        if isinstance(opt, str):
            opt = keras.optimizers.get(opt)
        return {
            "model_json": model.to_json(),
            "weights_blob": cloudpickle.dumps(model.get_weights()),
            "optimizer_config": json.dumps(
                keras.optimizers.serialize(opt)),
            "custom_objects": dict(self.getCustomObjects() or {}),
            "train_blob": cloudpickle.dumps((
                self.getLoss(), list(self.getMetrics() or []),
                list(self.getCallbacks() or []),
                self.getTransformationFn())),
        }

    def _remote_trainer(self):
        return _keras_trainer

    def _create_model(self, rank_results, run_id, store):
        import cloudpickle
        import keras

        weights = cloudpickle.loads(
            next(r["weights_blob"] for r in rank_results
                 if "weights_blob" in r))
        trained = keras.models.model_from_json(
            self.getModel().to_json(),
            custom_objects=dict(self.getCustomObjects() or {}))
        trained.set_weights(weights)
        return KerasModel(
            model=trained,
            feature_cols=list(self.getFeatureCols()),
            label_cols=list(self.getLabelCols()),
            output_cols=self.getOutputCols(),
            run_id=run_id, store=store,
            history=rank_results[0]["history"],
            batch_size=self.getBatchSize(),
        )


class KerasModel(HorovodModel):
    _param_defs = {"custom_objects": {}}

    def _predict_columns(self, features):
        import numpy as np

        model = self.getModel()
        xs = [features[c] for c in self.getFeatureCols()]
        x = xs[0] if len(xs) == 1 else xs
        out = model.predict(x, batch_size=self.getBatchSize(),
                            verbose=0)
        if not isinstance(out, (tuple, list)):
            out = [out]
        return [np.asarray(m).reshape(-1)
                if np.asarray(m).ndim == 2 and np.asarray(m).shape[1] == 1
                else (list(np.asarray(m)) if np.asarray(m).ndim > 1
                      else np.asarray(m))
                for m in out]
