"""TensorFlow / Keras frontend tests (parity model:
test/parallel/test_tensorflow.py + test_tensorflow2_keras.py; the
multi-rank data path is covered in test_multiprocess_tf.py).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402
import horovod_tpu.keras as hvd_keras  # noqa: E402


class TestTfOps:
    def test_allreduce_eager(self, hvt):
        out = hvd_tf.allreduce(tf.constant([1.0, 2.0]), op=hvd_tf.Sum)
        assert isinstance(out, tf.Tensor)
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0])

    def test_allreduce_graph_mode(self, hvt):
        @tf.function
        def step(t):
            return hvd_tf.allreduce(t, op=hvd_tf.Average)

        out = step(tf.constant([[2.0, 4.0]]))
        np.testing.assert_allclose(out.numpy(), [[2.0, 4.0]])
        assert out.shape == (1, 2)

    def test_allreduce_graph_mode_float64(self, hvt):
        # py_function's Tout contract: the engine computes at f32 wire
        # precision (jax x64 off) but the declared float64 dtype must be
        # restored, not error (regression: dtype-mismatch crash)
        @tf.function
        def step(t):
            return hvd_tf.allreduce(t, op=hvd_tf.Sum)

        out = step(tf.constant([1.5, 2.5], dtype=tf.float64))
        assert out.dtype == tf.float64
        np.testing.assert_allclose(out.numpy(), [1.5, 2.5])

    def test_alltoall_graph_mode_float64(self, hvt):
        @tf.function
        def step(t):
            return hvd_tf.alltoall(t, splits=tf.constant([2]))

        out, rsplits = step(tf.constant([1.5, 2.5], dtype=tf.float64))
        assert out.dtype == tf.float64
        np.testing.assert_allclose(out.numpy(), [1.5, 2.5])
        np.testing.assert_array_equal(rsplits.numpy(), [2])

    def test_allreduce_eager_float64_and_bfloat16(self, hvt):
        out = hvd_tf.allreduce(
            tf.constant([1.0, 2.0], dtype=tf.float64), op=hvd_tf.Sum
        )
        assert out.dtype == tf.float64
        out16 = hvd_tf.allreduce(
            tf.constant([1.0, 2.0], dtype=tf.bfloat16), op=hvd_tf.Sum
        )
        assert out16.dtype == tf.bfloat16
        np.testing.assert_allclose(
            tf.cast(out16, tf.float32).numpy(), [1.0, 2.0]
        )

    def test_allgather_and_broadcast(self, hvt):
        g = hvd_tf.allgather(tf.ones((3, 2)))
        assert g.shape == (3, 2)
        b = hvd_tf.broadcast(tf.constant([7.0]), root_rank=0)
        np.testing.assert_allclose(b.numpy(), [7.0])

    def test_alltoall_with_splits(self, hvt):
        out, rsplits = hvd_tf.alltoall(
            tf.constant([1.0, 2.0, 3.0]), splits=tf.constant([3])
        )
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0, 3.0])
        assert rsplits.numpy().tolist() == [3]

    def test_indexed_slices_allreduce(self, hvt):
        s = tf.IndexedSlices(
            values=tf.ones((2, 4)), indices=tf.constant([1, 3]),
            dense_shape=tf.constant([5, 4]),
        )
        r = hvd_tf.allreduce(s, op=hvd_tf.Average)
        assert isinstance(r, tf.IndexedSlices)
        np.testing.assert_allclose(r.values.numpy(), np.ones((2, 4)))
        assert r.indices.numpy().tolist() == [1, 3]

    def test_broadcast_variables(self, hvt):
        v1 = tf.Variable([1.0, 2.0])
        v2 = tf.Variable([[3.0]])
        hvd_tf.broadcast_variables([v1, v2], root_rank=0)
        np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])

    def test_broadcast_object_fn(self, hvt):
        # parity: hvd.broadcast_object_fn returns a bound bcast(obj)
        bcast = hvd_tf.broadcast_object_fn(root_rank=0)
        assert bcast({"k": 7}) == {"k": 7}

    def test_broadcast_object_roundtrip(self, hvt):
        obj = {"step": 12, "name": "x"}
        assert hvd_tf.broadcast_object(obj, root_rank=0) == obj
        assert hvd_tf.allgather_object(obj) == [obj]

    def test_broadcast_global_variables_eager_rejected(self, hvt):
        # TF1 surface: graph-mode only — eager users get pointed at
        # broadcast_variables instead of a silent empty-collection scan
        with pytest.raises(RuntimeError, match="graph-mode only"):
            hvd_tf.broadcast_global_variables(0)
        assert hasattr(hvd_tf, "BroadcastGlobalVariablesHook")

    def test_elastic_module_attribute(self, hvt):
        # parity: examples use `import horovod.tensorflow as hvd;
        # hvd.elastic.run(...)`
        assert hasattr(hvd_tf.elastic, "run")

    def test_tensorflow_keras_package_layout(self, hvt):
        # parity: the reference ships the keras surface at BOTH
        # horovod.keras and horovod.tensorflow.keras (shared impl in
        # horovod/_keras/); the canonical import path must work
        import horovod_tpu.tensorflow.keras as hvd_tfk

        assert hvd_tfk.DistributedOptimizer is hvd_keras.DistributedOptimizer
        assert hasattr(hvd_tfk.callbacks, "BroadcastGlobalVariablesCallback")
        # elastic.KerasState (horovod/tensorflow/keras/elastic.py)
        assert hasattr(hvd_tfk.elastic, "KerasState")
        assert hasattr(hvd_tfk.elastic, "run")
        import horovod_tpu.keras.elastic as k_elastic

        assert hasattr(k_elastic, "KerasState")

    def test_build_info_surface(self, hvt):
        assert hvd_tf.xla_built()
        assert not hvd_tf.nccl_built()
        assert hvd_tf.size() == 1 and hvd_tf.rank() == 0


class TestRegisteredGradients:
    """tf.custom_gradient registration on the bare collectives
    (parity: RegisterGradient('HorovodAllreduce'/'HorovodAllgather'/
    'HorovodBroadcast'/...) in horovod/tensorflow/mpi_ops.py).  At
    size 1 every rule degenerates to a checkable closed form; the
    cross-rank behavior is covered in test_multiprocess_tf."""

    def test_allreduce_grad_is_allreduce_of_grad(self, hvt):
        x = tf.constant([1.0, 2.0, 3.0])
        with tf.GradientTape() as t:
            t.watch(x)
            y = tf.reduce_sum(hvd_tf.allreduce(x * 2.0, op=hvd_tf.Sum))
        np.testing.assert_allclose(
            t.gradient(y, x).numpy(), [2.0, 2.0, 2.0])

    def test_allreduce_grad_in_graph_mode(self, hvt):
        x = tf.constant([1.0, 2.0])

        @tf.function
        def f(x):
            with tf.GradientTape() as t:
                t.watch(x)
                y = tf.reduce_sum(
                    hvd_tf.allreduce(x, op=hvd_tf.Average) * 4.0)
            return t.gradient(y, x)

        np.testing.assert_allclose(f(x).numpy(), [4.0, 4.0])

    def test_allreduce_minmax_grad_rejected(self, hvt):
        x = tf.constant([1.0])
        with tf.GradientTape() as t:
            t.watch(x)
            y = hvd_tf.allreduce(x, op=hvd_tf.Min)
        with pytest.raises(NotImplementedError, match="MIN"):
            t.gradient(y, x)

    def test_allgather_grad_slices_own_rows(self, hvt):
        x = tf.constant([[1.0], [1.0]])
        with tf.GradientTape() as t:
            t.watch(x)
            y = tf.reduce_sum(
                hvd_tf.allgather(x) * tf.constant([[2.0], [5.0]]))
        np.testing.assert_allclose(
            t.gradient(y, x).numpy(), [[2.0], [5.0]])

    def test_broadcast_grad_reduces_to_root(self, hvt):
        x = tf.constant([1.0, 1.0])
        with tf.GradientTape() as t:
            t.watch(x)
            y = tf.reduce_sum(hvd_tf.broadcast(x, root_rank=0) * 3.0)
        np.testing.assert_allclose(t.gradient(y, x).numpy(), [3.0, 3.0])

    def test_reducescatter_grad_is_allgather(self, hvt):
        x = tf.constant([[1.0], [2.0]])
        with tf.GradientTape() as t:
            t.watch(x)
            y = tf.reduce_sum(
                hvd_tf.reducescatter(x, op=hvd_tf.Sum) * 7.0)
        np.testing.assert_allclose(
            t.gradient(y, x).numpy(), [[7.0], [7.0]])

    def test_alltoall_grad_routes_back(self, hvt):
        x = tf.constant([1.0, 2.0, 3.0])
        with tf.GradientTape() as t:
            t.watch(x)
            out, _ = hvd_tf.alltoall(x, splits=[3])
            y = tf.reduce_sum(out * 5.0)
        np.testing.assert_allclose(
            t.gradient(y, x).numpy(), [5.0, 5.0, 5.0])

    def test_grouped_allreduce_grad(self, hvt):
        xs = [tf.constant([1.0, 1.0]), tf.constant([1.0, 1.0, 1.0])]
        with tf.GradientTape() as t:
            t.watch(xs)
            outs = hvd_tf.grouped_allreduce(xs, op=hvd_tf.Sum)
            y = tf.reduce_sum(outs[0] * 2.0) + tf.reduce_sum(
                outs[1] * 3.0)
        g0, g1 = t.gradient(y, xs)
        np.testing.assert_allclose(g0.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(g1.numpy(), [3.0, 3.0, 3.0])

    def test_alltoall_equal_splits_grad(self, hvt):
        x = tf.constant([1.0, 2.0])
        with tf.GradientTape() as t:
            t.watch(x)
            y = tf.reduce_sum(hvd_tf.alltoall(x) * 2.0)
        np.testing.assert_allclose(t.gradient(y, x).numpy(), [2.0, 2.0])

    def test_grouped_allgather_values_and_grad(self, hvt):
        xs = [tf.constant([[1.0], [2.0]]), tf.constant([[3.0, 4.0]])]
        with tf.GradientTape() as t:
            t.watch(xs)
            outs = hvd_tf.grouped_allgather(xs)
            y = (tf.reduce_sum(outs[0] * tf.constant([[2.0], [5.0]]))
                 + tf.reduce_sum(outs[1] * 3.0))
        np.testing.assert_allclose(outs[0].numpy(), [[1.0], [2.0]])
        np.testing.assert_allclose(outs[1].numpy(), [[3.0, 4.0]])
        g0, g1 = t.gradient(y, xs)
        np.testing.assert_allclose(g0.numpy(), [[2.0], [5.0]])
        np.testing.assert_allclose(g1.numpy(), [[3.0, 3.0]])

    def test_grouped_reducescatter_values_and_grad(self, hvt):
        xs = [tf.constant([[1.0], [2.0]]), tf.constant([3.0, 4.0])]
        with tf.GradientTape() as t:
            t.watch(xs)
            outs = hvd_tf.grouped_reducescatter(xs, op=hvd_tf.Sum)
            y = (tf.reduce_sum(outs[0] * 7.0)
                 + tf.reduce_sum(outs[1] * 2.0))
        np.testing.assert_allclose(outs[0].numpy(), [[1.0], [2.0]])
        np.testing.assert_allclose(outs[1].numpy(), [3.0, 4.0])
        g0, g1 = t.gradient(y, xs)
        np.testing.assert_allclose(g0.numpy(), [[7.0], [7.0]])
        np.testing.assert_allclose(g1.numpy(), [2.0, 2.0])

    def test_grouped_ops_graph_mode_fallback(self, hvt):
        @tf.function
        def step(a, b):
            outs = hvd_tf.grouped_allgather([a, b])
            red = hvd_tf.grouped_reducescatter([a, b], op=hvd_tf.Sum)
            return outs[0], red[1]

        o0, r1 = step(tf.constant([[1.0]]), tf.constant([2.0]))
        np.testing.assert_allclose(o0.numpy(), [[1.0]])
        np.testing.assert_allclose(r1.numpy(), [2.0])


class TestDistributedGradientTape:
    def test_gradients_pass_through(self, hvt):
        w = tf.Variable([[1.0], [2.0]])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.matmul(tf.ones((4, 2)), w))
        dtape = hvd_tf.DistributedGradientTape(tape)
        (g,) = dtape.gradient(loss, [w])
        np.testing.assert_allclose(g.numpy().ravel(), [4.0, 4.0])

    def test_none_gradient_preserved(self, hvt):
        w = tf.Variable([1.0])
        unused = tf.Variable([1.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * 2.0)
        dtape = hvd_tf.DistributedGradientTape(tape)
        g = dtape.gradient(loss, [w, unused])
        assert g[1] is None
        np.testing.assert_allclose(g[0].numpy(), [2.0])

    def test_predivide_average_equivalence(self, hvt):
        w = tf.Variable([3.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * 5.0)
        dtape = hvd_tf.DistributedGradientTape(
            tape, gradient_predivide_factor=2.0
        )
        (g,) = dtape.gradient(loss, [w])
        # predivide splits the averaging; single rank -> same value
        np.testing.assert_allclose(g.numpy(), [5.0])

    def test_context_manager_and_watch(self, hvt):
        """The proxy must preserve tape recording semantics: context
        manager entry/exit, watch() of a non-variable tensor."""
        x = tf.constant([2.0, 3.0])
        with hvd_tf.DistributedGradientTape(tf.GradientTape()) as dtape:
            dtape.watch(x)
            y = tf.reduce_sum(x * x)
        g = dtape.gradient(y, x)
        np.testing.assert_allclose(g.numpy(), [4.0, 6.0])

    def test_sparse_predivide_scaling(self, hvt):
        """IndexedSlices with gradient_predivide_factor must still
        average (Sum + pre/postscale == Average at size 1)."""
        emb = tf.Variable(tf.ones((4, 2)))
        with tf.GradientTape() as tape:
            rows = tf.gather(emb, [0, 2])
            loss = tf.reduce_sum(rows * 3.0)
        dtape = hvd_tf.DistributedGradientTape(
            tape, gradient_predivide_factor=2.0
        )
        (g,) = dtape.gradient(loss, [emb])
        assert isinstance(g, tf.IndexedSlices)
        np.testing.assert_allclose(g.values.numpy(),
                                   np.full((2, 2), 3.0))


class TestKerasOptimizer:
    def test_wrap_preserves_config(self, hvt):
        opt = keras.optimizers.SGD(learning_rate=0.25, momentum=0.9)
        dopt = hvd_keras.DistributedOptimizer(opt)
        assert type(dopt).__name__ == "DistributedSGD"
        assert dopt._hvtpu_distributed
        assert float(np.asarray(dopt.learning_rate)) == 0.25
        assert isinstance(dopt, keras.optimizers.Optimizer)

    def test_fit_converges(self, hvt):
        rng = np.random.RandomState(0)
        x = rng.rand(128, 8).astype(np.float32)
        y = x @ rng.rand(8, 1).astype(np.float32)
        model = keras.Sequential([keras.layers.Dense(1)])
        dopt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.2)
        )
        model.compile(optimizer=dopt, loss="mse")
        hist = model.fit(x, y, epochs=4, batch_size=32, verbose=0)
        losses = hist.history["loss"]
        assert losses[-1] < losses[0] * 0.5

    def test_backward_passes_per_step_aggregates(self, hvt):
        """bpps=2: variables move only every 2nd apply, by the
        averaged accumulated gradient (LocalGradientAggregationHelper
        parity)."""
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=1.0),
            backward_passes_per_step=2,
        )
        v = tf.Variable([10.0])
        opt.apply([tf.constant([2.0])], [v])   # micro-step: no move
        np.testing.assert_allclose(v.numpy(), [10.0])
        opt.apply([tf.constant([4.0])], [v])   # sync: avg(2,4)=3
        np.testing.assert_allclose(v.numpy(), [7.0])
        opt.apply([tf.constant([6.0])], [v])   # accumulation restarted
        np.testing.assert_allclose(v.numpy(), [7.0])
        opt.apply([tf.constant([0.0])], [v])   # sync: avg(6,0)=3
        np.testing.assert_allclose(v.numpy(), [4.0])

    def test_backward_passes_skip_stateful_updates(self, hvt):
        """Micro-steps must not touch stateful optimizer slots or
        iterations — with momentum, a zero-gradient apply would still
        move variables, so the base apply must be SKIPPED entirely."""
        opt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=1.0, momentum=0.9),
            backward_passes_per_step=2,
        )
        v = tf.Variable([10.0])
        opt.apply([tf.constant([2.0])], [v])
        opt.apply([tf.constant([2.0])], [v])   # sync: momentum kicks in
        after_first_sync = float(v.numpy()[0])
        assert int(opt.iterations.numpy()) == 1  # one aggregate step
        opt.apply([tf.constant([0.0])], [v])   # micro-step
        # momentum must NOT have been applied on the micro-step
        assert float(v.numpy()[0]) == after_first_sync
        assert int(opt.iterations.numpy()) == 1

    def test_backward_passes_per_step_in_fit(self, hvt):
        rng = np.random.RandomState(0)
        x = rng.rand(64, 4).astype(np.float32)
        y = x @ rng.rand(4, 1).astype(np.float32)
        model = keras.Sequential([keras.layers.Dense(1)])
        dopt = hvd_keras.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.4),
            backward_passes_per_step=2,
        )
        model.compile(optimizer=dopt, loss="mse")
        hist = model.fit(x, y, epochs=4, batch_size=16, verbose=0)
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_v1_optimizer_wrap(self, hvt):
        v1_opt = tf.compat.v1.train.GradientDescentOptimizer(0.1)
        dopt = hvd_tf.DistributedOptimizer(v1_opt)
        assert dopt.get_slot_names() == v1_opt.get_slot_names()

    def test_unsupported_optimizer_rejected(self, hvt):
        with pytest.raises(ValueError, match="unsupported optimizer"):
            hvd_tf.DistributedOptimizer(object())


class TestTensorFlowState:
    def test_variable_commit_restore_roundtrip(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowState

        v = tf.Variable([1.0, 2.0])
        w = tf.Variable([[3.0]])
        state = TensorFlowState(variables=[v, w], batch=0)
        state.commit()
        v.assign([9.0, 9.0])
        w.assign([[9.0]])
        state.batch = 7
        state.restore()
        np.testing.assert_allclose(v.numpy(), [1.0, 2.0])
        np.testing.assert_allclose(w.numpy(), [[3.0]])
        assert state.batch == 0

    def test_eager_requires_explicit_variables(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowState

        with pytest.raises(ValueError, match="explicit"):
            TensorFlowState()

    def test_refuses_partial_restore_on_var_count_mismatch(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowState

        state = TensorFlowState(
            variables=[tf.Variable([1.0]), tf.Variable([2.0])])
        with pytest.raises(ValueError, match="partial restore"):
            state._apply({"__vars__": [np.zeros(1)]})


class TestTensorFlowKerasState:
    def test_commit_restore_roundtrip(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

        model = keras.Sequential([keras.layers.Dense(2)])
        model.build((None, 3))
        state = TensorFlowKerasState(model, epoch=0)
        w0 = [w.copy() for w in model.get_weights()]
        state.commit()
        model.set_weights([w + 1.0 for w in model.get_weights()])
        state.epoch = 5
        state.restore()
        for a, b in zip(model.get_weights(), w0):
            np.testing.assert_allclose(a, b)
        assert state.epoch == 0

    def test_sync_broadcasts(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

        model = keras.Sequential([keras.layers.Dense(2)])
        model.build((None, 3))
        state = TensorFlowKerasState(model, epoch=3)
        state.sync()
        assert state.epoch == 3  # size-1 world: identity

    def test_restart_restores_momentum_into_fresh_optimizer(
            self, hvt, tmp_path, monkeypatch):
        # Elastic relaunch: the committed optimizer has built slot
        # variables (momentum), the fresh process's optimizer doesn't
        # — restore must build it and carry the slots over, not
        # silently truncate to the pre-build variable list.
        import tensorflow as tf

        from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))

        def make():
            m = keras.Sequential([keras.layers.Dense(1)])
            m.build((None, 2))
            return m, keras.optimizers.SGD(0.1, momentum=0.9)

        model, opt = make()
        opt.build(model.trainable_variables)
        n_built = len(opt.variables)
        for v in opt.variables:
            if "momentum" in v.path:
                v.assign(tf.fill(v.shape, 0.5))
        TensorFlowKerasState(model, optimizer=opt, epoch=1).commit()

        model2, opt2 = make()  # unbuilt: no momentum slots yet
        assert len(opt2.variables) < n_built
        state2 = TensorFlowKerasState(model2, optimizer=opt2, epoch=0)
        state2.sync()  # loads the durable commit
        assert state2.epoch == 1
        mom = [v for v in opt2.variables if "momentum" in v.path]
        assert mom and all(
            np.allclose(np.asarray(v), 0.5) for v in mom)

    def test_refuses_partial_optimizer_restore(self, hvt):
        from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

        model = keras.Sequential([keras.layers.Dense(1)])
        model.build((None, 2))
        opt = keras.optimizers.SGD(0.1, momentum=0.9)
        opt.build(model.trainable_variables)
        state = TensorFlowKerasState(model, optimizer=opt)
        with pytest.raises(ValueError, match="partial restore"):
            state._apply({"__opt_vars__": [np.zeros(1)]})


class TestSyncBatchNormalization:
    def test_single_rank_matches_vanilla_bn(self, hvt):
        # size-1 world: identical outputs AND identical moving-stat
        # updates as the base keras layer
        rng = np.random.RandomState(0)
        x = tf.constant(rng.rand(8, 4).astype(np.float32) * 3 + 1)
        sbn = hvd_tf.SyncBatchNormalization(momentum=0.9)
        bn = keras.layers.BatchNormalization(momentum=0.9)
        y_s = sbn(x, training=True)
        y_v = bn(x, training=True)
        np.testing.assert_allclose(y_s.numpy(), y_v.numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(sbn.moving_mean.numpy(),
                                   bn.moving_mean.numpy(), rtol=1e-5)
        np.testing.assert_allclose(sbn.moving_variance.numpy(),
                                   bn.moving_variance.numpy(),
                                   rtol=1e-5)

    def test_gradients_flow(self, hvt):
        x = tf.constant(
            np.random.RandomState(1).rand(8, 3).astype(np.float32))
        sbn = hvd_tf.SyncBatchNormalization()
        with tf.GradientTape() as tape:
            y = sbn(x, training=True)
            loss = tf.reduce_sum(y * y)
        grads = tape.gradient(loss, sbn.trainable_variables)
        assert len(grads) == 2 and all(g is not None for g in grads)

    def test_all_ranks_empty_batch_degrades_to_zeros(self, hvt,
                                                     monkeypatch):
        """ADVICE r5: a step where EVERY rank sees an empty batch
        (g_count == 0) must degrade to zero moments instead of
        poisoning the moving statistics with NaN."""
        from horovod_tpu.core import process_set as ps_mod

        sbn = hvd_tf.SyncBatchNormalization(momentum=0.5)
        sbn.build((None, 3))
        # simulate a 2-rank world whose fused stats allreduce returns
        # the packed sums unchanged (every rank contributed zero rows)
        monkeypatch.setattr(ps_mod, "participant_count", lambda ps: 2)
        monkeypatch.setattr(
            "horovod_tpu.tensorflow.mpi_ops.allreduce",
            lambda t, **kw: t)
        mean, variance = sbn._moments(tf.zeros((0, 3), tf.float32),
                                      None)
        assert np.all(mean.numpy() == 0.0)
        assert np.all(variance.numpy() == 0.0)
        y = sbn(tf.zeros((0, 3), tf.float32), training=True)
        assert y.shape == (0, 3)
        assert np.isfinite(sbn.moving_mean.numpy()).all()
        assert np.isfinite(sbn.moving_variance.numpy()).all()

    def test_config_roundtrips_process_set_id(self, hvt):
        sbn = hvd_tf.SyncBatchNormalization(
            momentum=0.8, process_set=hvd_tf.global_process_set)
        cfg = sbn.get_config()
        assert cfg["process_set"] == 0  # serialized as the set id
        assert cfg["momentum"] == 0.8
        rebuilt = hvd_tf.SyncBatchNormalization.from_config(cfg)
        assert rebuilt._process_set == 0  # engine resolves ids


class TestLoadModel:
    def test_load_model_wraps_and_preserves_state(self, hvt, tmp_path):
        # parity: hvd.load_model — the optimizer comes back as the
        # Distributed* subclass with saved state (iterations, Adam
        # slots) intact, and refit runs through the allreduce path
        model = keras.Sequential([
            keras.layers.Input((4,)), keras.layers.Dense(2)])
        model.compile(optimizer=keras.optimizers.Adam(0.01),
                      loss="mse")
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        y = rng.rand(32, 2).astype(np.float32)
        model.fit(x, y, epochs=2, verbose=0)
        it0 = int(model.optimizer.iterations)
        path = str(tmp_path / "m.keras")
        model.save(path)

        m2 = hvd_keras.load_model(path)
        assert type(m2.optimizer).__name__ == "DistributedAdam"
        assert m2.optimizer._hvtpu_distributed
        assert int(m2.optimizer.iterations) == it0
        slots = [v for v in m2.optimizer.variables
                 if "momentum" in v.path or "velocity" in v.path]
        assert slots and any(
            float(np.abs(np.asarray(v)).max()) > 0 for v in slots)
        m2.fit(x, y, epochs=1, verbose=0)
        assert int(m2.optimizer.iterations) == it0 + 1

    def test_load_model_roundtrips_wrapped_checkpoint(
            self, hvt, tmp_path):
        # a checkpoint SAVED from an already-wrapped optimizer
        # (class_name 'DistributedAdam') must reload: the wrapped
        # names are pre-registered as custom objects
        model = keras.Sequential([
            keras.layers.Input((4,)), keras.layers.Dense(2)])
        model.compile(
            optimizer=hvd_keras.DistributedOptimizer(
                keras.optimizers.Adam(0.01)),
            loss="mse")
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        y = rng.rand(32, 2).astype(np.float32)
        model.fit(x, y, epochs=2, verbose=0)
        path = str(tmp_path / "wrapped.keras")
        model.save(path)
        m2 = hvd_keras.load_model(path)
        assert m2.optimizer._hvtpu_distributed
        assert int(m2.optimizer.iterations) == 2
        m2.fit(x, y, epochs=1, verbose=0)
        assert int(m2.optimizer.iterations) == 3

    def test_load_model_available_on_tf_keras_path(self, hvt):
        import horovod_tpu.tensorflow.keras as hvd_tfk

        assert hvd_tfk.load_model is hvd_keras.load_model

    def test_load_model_without_optimizer(self, hvt, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((2,)), keras.layers.Dense(1)])
        path = str(tmp_path / "bare.keras")
        model.save(path)
        m2 = hvd_keras.load_model(path)
        assert getattr(m2, "optimizer", None) is None \
            or not getattr(m2.optimizer, "_hvtpu_distributed", False)


class TestElasticKerasCallbacks:
    """Parity: horovod/_keras/elastic.py — the callbacks the
    reference's elastic keras examples drive model.fit with."""

    def test_fit_maintains_state_and_commits(self, hvt):
        import horovod_tpu.tensorflow.keras as hvd_tfk

        model = keras.Sequential([keras.layers.Dense(1)])
        model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
        rng = np.random.RandomState(0)
        x = rng.rand(32, 4).astype(np.float32)
        y = x @ rng.rand(4, 1).astype(np.float32)

        state = hvd_tfk.elastic.KerasState(model, batch=0, epoch=0)
        commits = []
        orig = state.commit
        state.commit = lambda: (commits.append(True), orig())
        model.fit(
            x, y, batch_size=8, epochs=2, verbose=0,
            callbacks=[
                hvd_tfk.elastic.UpdateBatchStateCallback(state),
                hvd_tfk.elastic.UpdateEpochStateCallback(state),
                hvd_tfk.elastic.CommitStateCallback(
                    state, batches_per_commit=2),
            ])
        assert state.epoch == 2
        assert state.batch == 0  # reset at epoch end
        # 4 batches/epoch: commits at batch 2 and 4, plus epoch end
        assert len(commits) >= 4
        # the committed snapshot carries the post-fit epoch
        assert state._saved["epoch"] == 2

    def test_batch_callback_tracks_within_epoch(self, hvt):
        import horovod_tpu.keras.elastic as k_elastic

        class S:
            batch = 0
            epoch = 0

        s = S()
        cb = k_elastic.UpdateBatchStateCallback(s)
        cb.on_train_batch_end(5)
        assert s.batch == 6
        cb.on_epoch_end(0)
        assert s.batch == 0
        ecb = k_elastic.UpdateEpochStateCallback(s)
        ecb.on_epoch_end(3)
        assert s.epoch == 4

    def test_batch_callback_resumed_epoch_replays(self, hvt, caplog):
        # keras fit cannot skip into an epoch: a mid-epoch restore
        # replays the epoch from its start — the callback says so and
        # re-zeros the counter so in-epoch commits renumber correctly
        import logging

        import horovod_tpu.keras.elastic as k_elastic

        class S:
            batch = 3
            epoch = 1

        s = S()
        cb = k_elastic.UpdateBatchStateCallback(s)
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            cb.on_epoch_begin(1)
        assert s.batch == 0
        assert any("replays from its start" in r.message
                   for r in caplog.records)
        # a different epoch (not the interrupted one): no warning
        s2 = S()
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            k_elastic.UpdateBatchStateCallback(s2).on_epoch_begin(2)
        assert s2.batch == 3 and not caplog.records

    def test_commit_zero_batches_per_commit(self, hvt):
        import horovod_tpu.keras.elastic as k_elastic

        commits = []

        class S:
            def commit(self):
                commits.append(True)

        cb = k_elastic.CommitStateCallback(S(), batches_per_commit=0)
        for b in range(5):
            cb.on_batch_end(b)
        assert commits == []  # per-batch commits disabled
        cb.on_epoch_end(0)
        assert commits == [True]

    def test_commit_skips_final_batch_duplicate(self, hvt):
        # the epoch's final batch defers to the epoch-end commit
        # (same weights, updated counters) instead of snapshotting
        # twice back-to-back
        import horovod_tpu.keras.elastic as k_elastic

        commits = []

        class S:
            def commit(self):
                commits.append(True)

        cb = k_elastic.CommitStateCallback(S(), batches_per_commit=1)
        cb.params = {"steps": 4}
        for b in range(4):
            cb.on_batch_end(b)
        cb.on_epoch_end(0)
        # batches 0-2 commit; batch 3 (final) skips; epoch end commits
        assert len(commits) == 4


class TestKerasCallbacks:
    def _model(self):
        model = keras.Sequential([keras.layers.Dense(1)])
        model.compile(
            optimizer=keras.optimizers.SGD(learning_rate=0.1),
            loss="mse",
        )
        return model

    def _data(self):
        rng = np.random.RandomState(1)
        x = rng.rand(64, 4).astype(np.float32)
        return x, x @ rng.rand(4, 1).astype(np.float32)

    def test_broadcast_callback_runs(self, hvt):
        x, y = self._data()
        model = self._model()
        cb = hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0)
        model.fit(x, y, epochs=1, batch_size=32, verbose=0,
                  callbacks=[cb])
        assert cb.broadcast_done

    def test_metric_average_callback(self, hvt):
        x, y = self._data()
        model = self._model()
        model.fit(x, y, epochs=1, batch_size=32, verbose=0,
                  callbacks=[hvd_keras.callbacks.MetricAverageCallback()])

    def test_lr_warmup_reaches_size_multiple(self, hvt):
        x, y = self._data()
        model = self._model()
        cb = hvd_keras.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, initial_lr=0.1
        )
        model.fit(x, y, epochs=3, batch_size=32, verbose=0,
                  callbacks=[cb])
        # world size 1: warmup multiplier ends at 1.0
        assert float(np.asarray(model.optimizer.learning_rate)) \
            == pytest.approx(0.1)

    def test_lr_schedule_staircase(self, hvt):
        x, y = self._data()
        model = self._model()
        cb = hvd_keras.callbacks.LearningRateScheduleCallback(
            multiplier=lambda epoch: 0.5 ** epoch, start_epoch=0,
            initial_lr=0.1,
        )
        model.fit(x, y, epochs=3, batch_size=32, verbose=0,
                  callbacks=[cb])
        # epoch 2 multiplier: 0.25
        assert float(np.asarray(model.optimizer.learning_rate)) \
            == pytest.approx(0.025)


class TestGraphModeBroadcastFusion:
    """Graph-mode broadcast_variables must fuse per dtype group — one
    engine round-trip per dtype, not one per variable (N py_function
    hops at startup was the measured regression)."""

    def test_fused_one_call_per_dtype(self, hvt, monkeypatch):
        import horovod_tpu.tensorflow as hvd_tf
        from horovod_tpu.comm import eager as eager_comm

        calls = []
        real = eager_comm.broadcast

        def spy(tensor, **kw):
            calls.append(getattr(tensor, "shape", None))
            return real(tensor, **kw)

        monkeypatch.setattr(eager_comm, "broadcast", spy)

        vs = [tf.Variable(tf.fill((4, 2), float(i))) for i in range(5)]
        vs.append(tf.Variable(tf.constant([1, 2, 3], tf.int32)))

        @tf.function
        def do():
            hvd_tf.broadcast_variables(vs, root_rank=0)

        do()
        # 5 f32 variables fused into ONE broadcast + 1 int32 single
        assert len(calls) == 2, calls

    def test_fused_graph_values_correct(self, hvt):
        import horovod_tpu.tensorflow as hvd_tf

        vs = [tf.Variable(tf.fill((3,), float(i + 1))) for i in range(4)]

        @tf.function
        def do():
            hvd_tf.broadcast_variables(vs, root_rank=0)

        do()
        for i, v in enumerate(vs):
            np.testing.assert_allclose(v.numpy(), np.full((3,), i + 1.0))


class TestGraphTopologyOps:
    def test_size_rank_ops_in_graph(self, hvt):
        import horovod_tpu.tensorflow as hvd_tf

        @tf.function
        def f():
            return (hvd_tf.size_op() + hvd_tf.rank_op()
                    + hvd_tf.local_rank_op() + hvd_tf.local_size_op())

        assert int(f().numpy()) == 1 + 0 + 0 + 1
        assert hvd_tf.is_homogeneous() is True


def test_size_op_and_global_process_set(hvt):
    import pytest as _pytest

    import horovod_tpu.tensorflow as hvd_tf

    assert int(hvd_tf.size_op().numpy()) == 1
    assert hvd_tf.global_process_set.process_set_id == 0
    # non-global ids resolve through the live table (unknown id raises
    # rather than silently returning world size)
    with _pytest.raises(ValueError):
        hvd_tf.size_op(process_set_id=42)
