"""CLI for the fabric simulator (see package docstring)."""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys


def _ensure_deterministic_interpreter() -> None:
    """Re-exec once with PYTHONHASHSEED=0 so any hash-order-dependent
    iteration inside the interpreter is identical across runs — the
    byte-identical event-log contract must not hinge on hash
    randomisation."""
    if os.environ.get("PYTHONHASHSEED") == "0":
        return
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = "0"
    os.execve(sys.executable,
              [sys.executable, "-m", "tools.hvtpusim"] + sys.argv[1:],
              env)


def _parse_kv(pairs):
    """--set key=value scenario kwargs (ints/floats/bools parsed)."""
    out = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--set expects key=value, got {p!r}")
        k, v = p.split("=", 1)
        k = k.strip().replace("-", "_")
        v = v.strip()
        if v.lower() in ("true", "false"):
            out[k] = v.lower() == "true"
        else:
            try:
                out[k] = int(v)
            except ValueError:
                try:
                    out[k] = float(v)
                except ValueError:
                    out[k] = v
    return out


def _dump(result, out_path):
    lines = "".join(
        json.dumps(rec, sort_keys=True) + "\n" for rec in result["events"])
    digest = hashlib.sha256(lines.encode()).hexdigest()
    if out_path:
        with open(out_path, "w") as f:
            f.write(lines)
    return digest, len(result["events"])


def _cmd_list(_args) -> int:
    from horovod_tpu.sim.scenarios import SCENARIOS

    width = max(len(n) for n in SCENARIOS)
    for name, fn in sorted(SCENARIOS.items()):
        doc = (fn.__doc__ or "").strip().split("\n")[0]
        print(f"{name:<{width}}  {doc}")
    return 0


def _cmd_run(args) -> int:
    from horovod_tpu.sim.scenarios import run_scenario

    kwargs = _parse_kv(args.set)
    result = run_scenario(args.scenario, args.ranks, args.seed, **kwargs)
    digest, n_events = _dump(result, args.out)
    report = {
        "scenario": result["scenario"],
        "ranks": result["ranks"],
        "seed": result["seed"],
        "stats": result["stats"],
        "events": n_events,
        "event_log_sha256": digest,
    }
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


#: World sizes for the measured control-plane rows.  1024 is the
#: acceptance scale; 4096 works but is a coffee break, so it stays
#: opt-in via --ranks.
_BENCH_RANKS = (64, 256, 1024)


def bench_rows(ranks_list, seed: int = 0):
    """Measured control-plane timings vs world size: negotiation cycle
    (lockstep KVTransport exchange), rendezvous (audit digest
    allgather), and drain commit (notice → agreed durable commit).
    Virtual time on the default healthy-link model (50us latency,
    1 GbE, 10% jitter)."""
    from horovod_tpu.sim.scenarios import (bench_negotiation,
                                           steady_drain,
                                           thundering_rendezvous)

    rows = []
    for ranks in ranks_list:
        neg = bench_negotiation(ranks, seed)["stats"]["phases"]["negotiate"]
        rdv = thundering_rendezvous(ranks, seed)["stats"]["phases"][
            "rendezvous"]
        drn = steady_drain(ranks, seed)["stats"]["phases"]["drain"]
        rows.append({
            "ranks": ranks,
            "negotiation_cycle_p50_s": neg["cycle_p50_s"],
            "negotiation_cycle_max_s": neg["cycle_max_s"],
            "rendezvous_s": round(rdv["virtual_s"], 6),
            "rendezvous_p50_s": round(rdv["p50_s"], 6),
            "drain_notice_to_commit_s": drn["notice_to_commit_s"],
            "measured": True,
            "method": "fabric-sim virtual time, seed %d" % seed,
        })
        print(f"ranks={ranks}: negotiation p50 "
              f"{neg['cycle_p50_s'] * 1000:.2f} ms, rendezvous "
              f"{rdv['virtual_s']:.3f} s, drain notice→commit "
              f"{drn['notice_to_commit_s']:.3f} s", file=sys.stderr)
    return rows


def fleet_bench_rows(ranks_list, seed: int = 0):
    """Measured multi-job arbiter timings vs pool size: queue wait for
    a gang-scheduled high-priority arrival, preemption notice → agreed
    durable commit on the victim, and the victim's full resize latency
    (drain + relaunch at the smaller world).  Virtual time on the
    default healthy-link model."""
    import logging

    from horovod_tpu.sim.scenarios import multi_job_arbiter

    # every simulated rank shares this process's logger, so the
    # per-peer notice warning is O(ranks * victims) lines at 1024+ —
    # half a million for a bench that reports five numbers
    hvt_logger = logging.getLogger("horovod_tpu")
    prior_level = hvt_logger.level
    hvt_logger.setLevel(logging.ERROR)
    try:
        return _fleet_bench_rows(ranks_list, seed)
    finally:
        hvt_logger.setLevel(prior_level)


def _fleet_bench_rows(ranks_list, seed):
    from horovod_tpu.sim.scenarios import multi_job_arbiter

    rows = []
    for ranks in ranks_list:
        ph = multi_job_arbiter(ranks, seed)["stats"]["phases"]
        pre = ph["preempt"]
        rows.append({
            "ranks": ranks,
            "queue_wait_s": round(pre["queue_wait_s"], 6),
            "preempt_notice_to_commit_s": round(
                pre["notice_to_commit_s"], 6),
            "resize_s": round(pre["resize_s"], 6),
            "victims": pre["victims"],
            "measured": True,
            "method": "fabric-sim virtual time, seed %d" % seed,
        })
        print(f"ranks={ranks}: queue wait {pre['queue_wait_s']:.3f} s, "
              f"preempt notice→commit {pre['notice_to_commit_s']:.3f} s, "
              f"resize {pre['resize_s']:.3f} s "
              f"({pre['victims']} victims)", file=sys.stderr)
    return rows


def _cmd_bench_fleet(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = fleet_bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"fleet_arbiter_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["fleet_arbiter_sim"] = {
            "note": (
                "MEASURED on the fabric simulator: the real FleetArbiter "
                "(horovod_tpu/fleet) arbitrating two jobs over one "
                "virtual pool — a high-priority gang arrival preempts "
                "half the low-priority world through the graceful-drain "
                "channel (exit 79, zero budget strikes).  queue_wait_s "
                "is submit → gang placement for the arrival; "
                "preempt_notice_to_commit_s is drain notice → agreed "
                "durable commit on the victim; resize_s is notice → "
                "relaunch at the smaller world."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


def ckpt_bench_rows(ranks_list, seed: int = 0):
    """Measured durable-state-plane timings vs world size: snapshot
    commit latency (modeled disk + the real commit protocol) and the
    restore-quorum agreement time under injected torn/bitflip damage
    (checkpoint-storm scenario).  Virtual time on the default
    healthy-link model."""
    import logging

    # the two storage-damage victims log warnings through the shared
    # process logger; silence them for a bench that reports numbers
    hvt_logger = logging.getLogger("horovod_tpu")
    prior_level = hvt_logger.level
    hvt_logger.setLevel(logging.ERROR)
    try:
        return _ckpt_bench_rows(ranks_list, seed)
    finally:
        hvt_logger.setLevel(prior_level)


def _ckpt_bench_rows(ranks_list, seed):
    from horovod_tpu.sim.scenarios import checkpoint_storm

    rows = []
    for ranks in ranks_list:
        ph = checkpoint_storm(ranks, seed)["stats"]["phases"]
        cm, rq = ph["commit"], ph["restore_quorum"]
        rows.append({
            "ranks": ranks,
            "commit_p50_s": cm["commit_p50_s"],
            "commit_p99_s": cm["commit_p99_s"],
            "quorum_p50_s": rq["quorum_p50_s"],
            "quorum_max_s": rq["quorum_max_s"],
            "agreed_seq": rq["agreed_seq"],
            "measured": True,
            "method": "fabric-sim virtual time, seed %d" % seed,
        })
        print(f"ranks={ranks}: commit p50 "
              f"{cm['commit_p50_s'] * 1000:.2f} ms, restore quorum p50 "
              f"{rq['quorum_p50_s'] * 1000:.2f} ms / max "
              f"{rq['quorum_max_s'] * 1000:.2f} ms", file=sys.stderr)
    return rows


def _cmd_bench_ckpt(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = ckpt_bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"checkpoint_storm_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["checkpoint_storm_sim"] = {
            "note": (
                "MEASURED on the fabric simulator: the real durable "
                "commit protocol (horovod_tpu/core/durable.py) at "
                "virtual scale with injected ckpt.write torn/bitflip "
                "damage on two victims' final commit.  commit_*_s is "
                "one snapshot commit (modeled disk at 200 MB/s + 2 ms "
                "base, payload writes + manifest rename); quorum_*_s "
                "is one rank's restore-quorum round (publish highest "
                "verified seq, blocking-read all peers, agree on the "
                "min).  The damaged commits lower the agreed seq by "
                "one — never diverge it."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


def anomaly_bench_rows(ranks_list, seed: int = 0, seeds: int = 5):
    """Measured straggler-detection latency vs world size: virtual
    seconds from a mid-run ``set_link`` degradation of one rank to the
    first straggler incident naming exactly that rank
    (anomaly-detection scenario, real AnomalyEngine).  p50/max over
    ``seeds`` independent seeds per world size."""
    from horovod_tpu.sim.scenarios import anomaly_detection

    rows = []
    for ranks in ranks_list:
        lats = []
        for s in range(seed, seed + seeds):
            ph = anomaly_detection(ranks, s)["stats"]["phases"]["detect"]
            lats.append(ph["detection_latency_s"])
        lats.sort()
        rows.append({
            "ranks": ranks,
            "detection_latency_p50_s": round(
                lats[len(lats) // 2], 6),
            "detection_latency_max_s": round(lats[-1], 6),
            "seeds": seeds,
            "measured": True,
            "method": "fabric-sim virtual time, seeds %d..%d" % (
                seed, seed + seeds - 1),
        })
        print(f"ranks={ranks}: detection latency p50 "
              f"{lats[len(lats) // 2]:.3f} s, max {lats[-1]:.3f} s "
              f"({seeds} seeds)", file=sys.stderr)
    return rows


def _cmd_bench_anomaly(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = anomaly_bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"anomaly_detection_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["anomaly_detection_sim"] = {
            "note": (
                "MEASURED on the fabric simulator: the real "
                "AnomalyEngine (horovod_tpu/obs/anomaly.py) fed "
                "per-cycle arrival skew while one virtual rank's link "
                "degrades 400x mid-run via set_link.  "
                "detection_latency_*_s is virtual seconds from the "
                "degradation to the first straggler incident; the "
                "scenario asserts the incident names exactly the "
                "degraded rank."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


#: World sizes for the fleet-service front-door rows: the tier-1
#: storm plus the 4096/16384 scale proofs from the acceptance bar.
_SERVICE_BENCH_RANKS = (256, 4096, 16384)


def service_bench_rows(ranks_list, seed: int = 0):
    """Measured fleet front-door rows vs pool size: queue-wait
    percentiles by priority tier, submit→intake latency through the
    indexed journal, pool fragmentation, preemption churn, and the
    starvation guard's observed bound — all from the fleet-service
    storm scenario (which internally asserts exactly-once intake
    across an injected arbiter crash)."""
    from horovod_tpu.sim.scenarios import fleet_service

    rows = []
    for ranks in ranks_list:
        ph = fleet_service(ranks, seed)["stats"]["phases"]
        svc = ph["service"]
        rows.append({
            "ranks": ranks,
            "jobs": ph["pool"]["jobs"],
            "queue_wait_p50_s": svc["queue_wait_p50_s"],
            "queue_wait_p99_s": svc["queue_wait_p99_s"],
            "intake_p50_s": ph["intake"]["intake_p50_s"],
            "intake_p99_s": ph["intake"]["intake_p99_s"],
            "max_batch": ph["intake"]["max_batch"],
            "queue_full_rejections": ph["intake"][
                "queue_full_rejections"],
            "quota_rejections": ph["admission"]["rejected"],
            "replayed_duplicates": ph["crash"]["replayed_duplicates"],
            "frag_mean": ph["placement"]["frag_mean"],
            "preemptions": svc["preemptions"],
            "aged_jobs": svc["aged_jobs"],
            "starvation_gap_max_s": svc["aged_gap_max_s"],
            "measured": True,
            "method": "fabric-sim virtual time, seed %d" % seed,
        })
        print(f"ranks={ranks}: {ph['pool']['jobs']} jobs, "
              f"tier-0 wait p99 "
              f"{svc['queue_wait_p99_s']['0']:.1f} s, intake p99 "
              f"{ph['intake']['intake_p99_s']:.3f} s, frag "
              f"{ph['placement']['frag_mean']:.3f}, "
              f"{svc['preemptions']} preemptions", file=sys.stderr)
    return rows


def _cmd_bench_service(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = service_bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"fleet_service_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["fleet_service_sim"] = {
            "note": (
                "MEASURED on the fabric simulator: the production "
                "front door end to end — a seeded multi-tenant "
                "submission storm through the REAL indexed journal "
                "(fleet/intake.py) into the REAL arbiter with "
                "tenants.json quotas, weighted fair share, the "
                "starvation guard, torus-aware placement, truthful "
                "queue-full backpressure, and an injected arbiter "
                "crash that rolls the intake cursor back mid-storm.  "
                "queue_wait_*_s keys by priority tier; intake_*_s is "
                "submit append -> arbiter intake; "
                "starvation_gap_max_s bounds aged-job wait past the "
                "aging threshold.  The scenario internally asserts "
                "exactly-once intake across the crash and a per-tick "
                "cost bounded by the intake budget."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


def lossy_bench_rows(ranks_list, seed: int = 7):
    """Measured wire-plane recovery rows vs world size: the lossy-link
    scenario with consensus abort-and-retry armed (zero restarts, zero
    torn collectives — asserted inside the scenario) against the SAME
    seed with retries disabled, where the first wire loss poisons the
    job and every later step is lost to the restart."""
    import logging

    # each consensus retry and reroute logs a warning through the
    # shared process logger; silence them for a bench that reports rows
    hvt_logger = logging.getLogger("horovod_tpu")
    prior_level = hvt_logger.level
    hvt_logger.setLevel(logging.ERROR)
    try:
        return _lossy_bench_rows(ranks_list, seed)
    finally:
        hvt_logger.setLevel(prior_level)


def _lossy_bench_rows(ranks_list, seed):
    from horovod_tpu.sim.scenarios import lossy_link

    rows = []
    for ranks in ranks_list:
        ll = lossy_link(ranks, seed)["stats"]["phases"]["lossy_link"]
        base = lossy_link(ranks, seed, baseline=True)[
            "stats"]["phases"]["lossy_link"]
        rows.append({
            "ranks": ranks,
            "steps": ll["steps"],
            "retry_rounds": ll["retry_rounds"],
            "recovered_collectives": ll["recovered_collectives"],
            "consensus_p50_s": ll["consensus_p50_s"],
            "consensus_max_s": ll["consensus_max_s"],
            "reroutes": ll["reroutes"],
            "torn": ll["torn"],
            "steps_lost_with_retries": ll["steps_lost"],
            "baseline_restarts": base["restarts"],
            "baseline_steps_lost": base["steps_lost"],
            "measured": True,
            "method": "fabric-sim virtual time, seed %d" % seed,
        })
        print(f"ranks={ranks}: {ll['recovered_collectives']} collectives "
              f"recovered over {ll['retry_rounds']} consensus rounds "
              f"(p50 {ll['consensus_p50_s'] * 1000:.1f} ms), "
              f"{ll['reroutes']} reroutes, {ll['torn']} torn; baseline "
              f"loses {base['steps_lost']}/{ll['steps']} steps to the "
              f"restart", file=sys.stderr)
    return rows


def _cmd_bench_lossy(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = lossy_bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"lossy_link_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["lossy_link_sim"] = {
            "note": (
                "MEASURED on the fabric simulator: the wire plane "
                "under a lossy fabric — seeded per-edge drops, a "
                "mid-run link flap, and deterministic wire.send drop "
                "injections — recovered by the REAL consensus "
                "abort-and-retry protocol (comm/wirefault.py) over "
                "the fabric KV, with the REAL LinkHealth map rerouting "
                "the ring around the flapping rank.  The scenario "
                "asserts zero restarts and zero torn collectives "
                "(every retried delivery bitwise-equal to the clean "
                "run); consensus_*_s is vote post -> agreed decision.  "
                "baseline_* rows re-run the SAME seed with retries "
                "disabled: the first loss poisons the job and "
                "baseline_steps_lost of the run's steps are lost to "
                "the restart-the-world recovery."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


def _cmd_bench(args) -> int:
    ranks_list = [int(r) for r in args.ranks.split(",") if r.strip()]
    rows = bench_rows(ranks_list, seed=args.seed)
    print(json.dumps({"control_plane_sim": rows}, indent=1,
                     sort_keys=True))
    if args.update:
        path = args.update
        with open(path) as f:
            doc = json.load(f)
        doc["control_plane_sim"] = {
            "note": (
                "MEASURED on the fabric simulator (horovod_tpu/sim): "
                "real KVTransport/audit/drain code over the virtual-"
                "time KV with the default link model (50us, 1GbE, 10% "
                "jitter).  Supersedes the coordination_vs_P projection "
                "for control-plane scaling: these are protocol-"
                "faithful virtual-time measurements at the stated "
                "world sizes, not extrapolations."),
            "rows": rows,
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {path}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    _ensure_deterministic_interpreter()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="hvtpusim",
        description="run the hvtpu control plane at virtual scale")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_run = sub.add_parser("run", help="run one named scenario")
    p_run.add_argument("scenario")
    p_run.add_argument("--ranks", type=int, default=256)
    p_run.add_argument("--seed", type=int, default=0)
    p_run.add_argument("--out", help="write the event log (JSONL) here")
    p_run.add_argument("--set", action="append", metavar="KEY=VAL",
                       help="scenario keyword override (repeatable)")
    p_run.set_defaults(fn=_cmd_run)
    p_list = sub.add_parser("list", help="list scenarios")
    p_list.set_defaults(fn=_cmd_list)
    p_bench = sub.add_parser(
        "bench", help="measured control-plane scaling rows")
    p_bench.add_argument(
        "--ranks", default=",".join(str(r) for r in _BENCH_RANKS))
    p_bench.add_argument("--seed", type=int, default=0)
    p_bench.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_bench.set_defaults(fn=_cmd_bench)
    p_fleet = sub.add_parser(
        "bench-fleet", help="measured multi-job arbiter scaling rows")
    p_fleet.add_argument(
        "--ranks", default=",".join(str(r) for r in _BENCH_RANKS))
    p_fleet.add_argument("--seed", type=int, default=0)
    p_fleet.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_fleet.set_defaults(fn=_cmd_bench_fleet)
    p_ckpt = sub.add_parser(
        "bench-ckpt", help="measured durable-state-plane scaling rows")
    p_ckpt.add_argument(
        "--ranks", default=",".join(str(r) for r in _BENCH_RANKS))
    p_ckpt.add_argument("--seed", type=int, default=0)
    p_ckpt.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_ckpt.set_defaults(fn=_cmd_bench_ckpt)
    p_anom = sub.add_parser(
        "bench-anomaly",
        help="measured straggler-detection latency rows")
    p_anom.add_argument("--ranks", default="256,1024")
    p_anom.add_argument("--seed", type=int, default=0)
    p_anom.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_anom.set_defaults(fn=_cmd_bench_anomaly)
    p_svc = sub.add_parser(
        "bench-service",
        help="measured fleet front-door (service) scaling rows")
    p_svc.add_argument(
        "--ranks",
        default=",".join(str(r) for r in _SERVICE_BENCH_RANKS))
    p_svc.add_argument("--seed", type=int, default=0)
    p_svc.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_svc.set_defaults(fn=_cmd_bench_service)
    p_lossy = sub.add_parser(
        "bench-lossy",
        help="measured wire-plane recovery-vs-restart rows")
    p_lossy.add_argument(
        "--ranks", default=",".join(str(r) for r in _BENCH_RANKS))
    p_lossy.add_argument("--seed", type=int, default=7)
    p_lossy.add_argument(
        "--update", metavar="BENCH_SCALING.json",
        help="write the rows into this bench JSON")
    p_lossy.set_defaults(fn=_cmd_bench_lossy)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
