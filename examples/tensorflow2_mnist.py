"""TF2 custom-loop MNIST example — the horovod_tpu analog of the
reference's examples/tensorflow2/tensorflow2_mnist.py: a
tf.GradientTape training loop with ``DistributedGradientTape``,
rank-0 variable broadcast after the first step, and lr scaled by
world size.  The hvd calls match the reference pattern one-for-one;
synthetic MNIST-shaped data (no tf.data download) keeps it hermetic.

Run:  hvtpurun -np 2 --cpu-devices 1 python examples/tensorflow2_mnist.py
"""

import argparse

import numpy as np

import horovod_tpu.tensorflow as hvd


def main():
    import keras
    import tensorflow as tf

    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")
    if args.batch_size < 1:
        p.error("--batch-size must be >= 1")

    hvd.init()
    np.random.seed(0)
    x = np.random.rand(1024, 784).astype(np.float32)
    w = np.random.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.int64)

    # shard by rank (DistributedSampler analog)
    n = len(x) // hvd.size()
    lo = hvd.rank() * n
    xs, ys = x[lo:lo + n], y[lo:lo + n]

    model = keras.Sequential([
        keras.layers.Input((784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])
    loss_fn = keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    opt = keras.optimizers.SGD(0.05 * hvd.size())

    def training_step(bx, by, first_batch):
        with tf.GradientTape() as tape:
            probs = model(bx, training=True)
            loss = loss_fn(by, probs)
        # the tape wrapper averages gradients across ranks
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first_batch:
            # broadcast AFTER the first step so optimizer slots exist
            # (reference pattern: hvd.broadcast_variables on both)
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
        return loss

    for step in range(args.steps):
        # wrap over the whole shard (the tail batch may be short)
        i = (step * args.batch_size) % len(xs)
        loss = training_step(
            tf.constant(xs[i:i + args.batch_size]),
            tf.constant(ys[i:i + args.batch_size]), step == 0)
        if step % 8 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss={float(loss):.4f}", flush=True)

    final = hvd.allreduce(loss, op=hvd.Average)
    if hvd.rank() == 0:
        print(f"final loss {float(final):.4f}; ranks consistent "
              f"({hvd.size()} ranks)", flush=True)


if __name__ == "__main__":
    main()
