"""wire-twin pass: C++ wire ABI vs the Python twin, without compiling.

Surfaces checked (all byte-layout-relevant):

  * kRequestMagic / kResponseMagic / kWireVersion (message.h) vs
    REQUEST_MAGIC / RESPONSE_MAGIC / WIRE_VERSION (native/wire.py)
  * OpType / RedOp / DataType enum values (common.h) vs the range()
    tuples and DTYPE_IDS in wire.py, both directions
  * DataTypeSize() switch vs DTYPE_SIZES
  * serialized field order: the ordered writer-op programs of
    WriteEntry / SerializeRequestList / SerializeResponseList
    (message.cc) vs _write_entry / serialize_request_list /
    serialize_response_list (wire.py)
  * burst-unit delimiter position (wire v5): the burst_id/burst_len
    u32 pair must sit immediately after the flags byte of the
    RequestList header in both twins
  * ResponseCache::Signature field order (controller.cc) vs
    Entry.signature, and the '\\x01' message-table key separator
    (controller.cc vs native/fallback.py)

The runtime byte-agreement tests still exist; this pass catches the
same drift at lint time and — unlike those tests — does not need a
C++ toolchain.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from . import Finding, Project
from . import cppscan

PASS = "wire-twin"

MESSAGE_H = "horovod_tpu/native/src/message.h"
COMMON_H = "horovod_tpu/native/src/common.h"
MESSAGE_CC = "horovod_tpu/native/src/message.cc"
CONTROLLER_CC = "horovod_tpu/native/src/controller.cc"
WIRE_PY = "horovod_tpu/native/wire.py"
FALLBACK_PY = "horovod_tpu/native/fallback.py"

# C++ constant -> Python twin constant.
CONSTANT_TWINS = {
    "kRequestMagic": "REQUEST_MAGIC",
    "kResponseMagic": "RESPONSE_MAGIC",
    "kWireVersion": "WIRE_VERSION",
}

# C++ serialize function -> Python twin function.
ORDER_TWINS = {
    "WriteEntry": "_write_entry",
    "SerializeRequestList": "serialize_request_list",
    "SerializeResponseList": "serialize_response_list",
}


def _py_constants(tree: ast.Module) -> Dict[str, Tuple[int, int]]:
    """Module-level `NAME = <int literal>` -> (value, line)."""
    out: Dict[str, Tuple[int, int]] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            out[node.targets[0].id] = (node.value.value, node.lineno)
    return out


def _py_enum_tuples(tree: ast.Module) -> List[Tuple[List[str], int, int]]:
    """`A, B, C = range(n)` assigns -> ([names], n, line)."""
    out = []
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "range"
                and len(node.value.args) == 1
                and isinstance(node.value.args[0], ast.Constant)):
            names = [t.id for t in node.targets[0].elts
                     if isinstance(t, ast.Name)]
            out.append((names, node.value.args[0].value, node.lineno))
    return out


def _py_dict(tree: ast.Module, name: str) -> Optional[Tuple[dict, int]]:
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Dict)):
            try:
                d = ast.literal_eval(node.value)
            except ValueError:
                return None
            return d, node.lineno
    return None


def _py_write_sequence(tree: ast.Module, func_name: str) -> Optional[List[str]]:
    """Ordered writer-op sequence of a wire.py serialize function.

    Collects `w.<op>(...)` calls plus `_write_entry(...)` calls in
    source order; the writer method `s` normalizes to the C++ `str`.
    """
    fn = next((n for n in tree.body
               if isinstance(n, ast.FunctionDef) and n.name == func_name),
              None)
    if fn is None:
        return None
    events: List[Tuple[int, int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "w"
                and f.attr in {"u8", "u32", "i32", "i64", "u64", "f64", "s"}):
            op = "str" if f.attr == "s" else f.attr
            events.append((node.lineno, node.col_offset, op))
        elif isinstance(f, ast.Name) and f.id == "_write_entry":
            events.append((node.lineno, node.col_offset, "entry"))
    events.sort()
    return [op for _, _, op in events]


_CPP_FIELD_RE = re.compile(r"\be\.(\w+)")
_PY_FIELD_RE = re.compile(r"self\.(\w+)")


def _signature_fields_cpp(body: str) -> List[str]:
    seen: List[str] = []
    for m in _CPP_FIELD_RE.finditer(body):
        if m.group(1) not in seen:
            seen.append(m.group(1))
    return seen


def _self_fields_in(node: ast.expr) -> List[str]:
    """self.<field> reads under `node`, in source order, deduped."""
    hits: List[Tuple[int, int, str]] = []
    for n in ast.walk(node):
        if (isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name)
                and n.value.id == "self"):
            hits.append((n.lineno, n.col_offset, n.attr))
    hits.sort()
    out: List[str] = []
    for _, _, attr in hits:
        if attr not in out:
            out.append(attr)
    return out


def _signature_fields_py(src: str, tree: ast.Module) -> Tuple[List[str], int]:
    """Field *emission* order of Entry.signature().

    Locals assigned from self.<field> expressions (`dims` built from
    self.shape) resolve to their source fields at the position where
    the local is interpolated, so the order reflects the produced
    string, not textual appearance.
    """
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "Entry"):
            continue
        for item in node.body:
            if not (isinstance(item, ast.FunctionDef)
                    and item.name == "signature"):
                continue
            local_fields: Dict[str, List[str]] = {}
            ret: Optional[ast.Return] = None
            for n in ast.walk(item):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)):
                    local_fields[n.targets[0].id] = _self_fields_in(n.value)
                elif isinstance(n, ast.Return) and n.value is not None:
                    ret = n
            if ret is None:
                return [], item.lineno
            hits: List[Tuple[int, int, List[str]]] = []
            for n in ast.walk(ret.value):
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"):
                    hits.append((n.lineno, n.col_offset, [n.attr]))
                elif isinstance(n, ast.Name) and n.id in local_fields:
                    hits.append((n.lineno, n.col_offset,
                                 local_fields[n.id]))
            hits.sort(key=lambda h: (h[0], h[1]))
            seen: List[str] = []
            for _, _, fields in hits:
                for f in fields:
                    if f not in seen:
                        seen.append(f)
            return seen, item.lineno
    return [], 0


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    msg_h = project.read(MESSAGE_H)
    common_h = project.read(COMMON_H)
    msg_cc = project.read(MESSAGE_CC)
    ctrl_cc = project.read(CONTROLLER_CC)
    wire_src = project.read(WIRE_PY)
    wire_ast = project.parse(WIRE_PY)
    fallback_src = project.read(FALLBACK_PY)

    for rel, content in [(MESSAGE_H, msg_h), (COMMON_H, common_h),
                         (MESSAGE_CC, msg_cc), (CONTROLLER_CC, ctrl_cc),
                         (WIRE_PY, wire_src), (FALLBACK_PY, fallback_src)]:
        if content is None:
            findings.append(project.missing(PASS, rel))
    if None in (msg_h, common_h, msg_cc, ctrl_cc, wire_src, fallback_src) \
            or wire_ast is None:
        return findings

    # -- magic numbers and wire version --------------------------------
    cpp_consts = cppscan.constants(msg_h)
    py_consts = _py_constants(wire_ast)
    for cpp_name, py_name in CONSTANT_TWINS.items():
        if cpp_name not in cpp_consts:
            findings.append(Finding(
                PASS, MESSAGE_H, 0, f"const:{cpp_name}",
                f"constant {cpp_name} not found in message.h"))
            continue
        if py_name not in py_consts:
            findings.append(Finding(
                PASS, WIRE_PY, 0, f"const:{cpp_name}",
                f"twin constant {py_name} not found in wire.py"))
            continue
        cv = cpp_consts[cpp_name]
        pv, pline = py_consts[py_name]
        if cv != pv:
            findings.append(Finding(
                PASS, WIRE_PY, pline, f"const:{cpp_name}",
                f"{py_name}=0x{pv:x} disagrees with "
                f"{cpp_name}=0x{cv:x} "
                f"({MESSAGE_H}:{cppscan.const_line(msg_h, cpp_name)})"))

    # -- enum values ----------------------------------------------------
    cpp_enums = cppscan.enums(common_h)
    tuples = _py_enum_tuples(wire_ast)
    py_optype = next((dict(zip(names, range(n)))
                      for names, n, _ in tuples
                      if names and not names[0].startswith("RED_")), {})
    py_redop = next((dict(zip(names, range(n)))
                     for names, n, _ in tuples
                     if names and names[0].startswith("RED_")), {})

    def check_enum(cpp_name: str, py_map: Dict[str, int],
                   to_py: "callable") -> None:
        cpp_map = cpp_enums.get(cpp_name)
        if cpp_map is None:
            findings.append(Finding(
                PASS, COMMON_H, 0, f"enum:{cpp_name}",
                f"enum class {cpp_name} not found in common.h"))
            return
        if not py_map:
            findings.append(Finding(
                PASS, WIRE_PY, 0, f"enum:{cpp_name}",
                f"Python twin of enum {cpp_name} not found in wire.py"))
            return
        for member, val in cpp_map.items():
            py_name = to_py(member)
            if py_name not in py_map:
                findings.append(Finding(
                    PASS, WIRE_PY, 0, f"enum:{cpp_name}:{member}",
                    f"{cpp_name}::k{member}={val} has no Python twin "
                    f"{py_name}"))
            elif py_map[py_name] != val:
                findings.append(Finding(
                    PASS, WIRE_PY, 0, f"enum:{cpp_name}:{member}",
                    f"{py_name}={py_map[py_name]} disagrees with "
                    f"{cpp_name}::k{member}={val}"))
        cpp_twins = {to_py(m) for m in cpp_map}
        for py_name in py_map:
            if py_name not in cpp_twins:
                findings.append(Finding(
                    PASS, WIRE_PY, 0, f"enum:{cpp_name}:{py_name}",
                    f"{py_name} has no {cpp_name} member in common.h"))

    check_enum("OpType", py_optype, lambda m: m.upper())
    check_enum("RedOp", py_redop, lambda m: "RED_" + m.upper())

    dtype_ids = _py_dict(wire_ast, "DTYPE_IDS")
    cpp_dtypes = cpp_enums.get("DataType")
    if cpp_dtypes is None:
        findings.append(Finding(PASS, COMMON_H, 0, "enum:DataType",
                                "enum class DataType not found in common.h"))
    elif dtype_ids is None:
        findings.append(Finding(PASS, WIRE_PY, 0, "enum:DataType",
                                "DTYPE_IDS dict not found in wire.py"))
    else:
        ids, ids_line = dtype_ids
        for member, val in cpp_dtypes.items():
            py_name = member.lower()
            if py_name not in ids:
                findings.append(Finding(
                    PASS, WIRE_PY, ids_line, f"enum:DataType:{member}",
                    f"DataType::k{member}={val} missing from DTYPE_IDS"))
            elif ids[py_name] != val:
                findings.append(Finding(
                    PASS, WIRE_PY, ids_line, f"enum:DataType:{member}",
                    f"DTYPE_IDS[{py_name!r}]={ids[py_name]} disagrees "
                    f"with DataType::k{member}={val}"))
        cpp_names = {m.lower() for m in cpp_dtypes}
        for py_name in ids:
            if py_name not in cpp_names:
                findings.append(Finding(
                    PASS, WIRE_PY, ids_line, f"enum:DataType:{py_name}",
                    f"DTYPE_IDS[{py_name!r}] has no DataType member"))

        # element sizes, joined on the dtype id
        sizes = _py_dict(wire_ast, "DTYPE_SIZES")
        cpp_sizes, cpp_default = cppscan.datatype_size_map(common_h)
        if sizes is None:
            findings.append(Finding(PASS, WIRE_PY, 0, "dtype-sizes",
                                    "DTYPE_SIZES dict not found in wire.py"))
        elif not cpp_sizes and cpp_default is None:
            findings.append(Finding(
                PASS, COMMON_H, 0, "dtype-sizes",
                "could not parse DataTypeSize() switch in common.h"))
        else:
            sz, sz_line = sizes
            cpp_by_id = {
                val: cpp_sizes.get(member, cpp_default)
                for member, val in cpp_dtypes.items()
            }
            if sz != cpp_by_id:
                findings.append(Finding(
                    PASS, WIRE_PY, sz_line, "dtype-sizes",
                    f"DTYPE_SIZES={sz} disagrees with DataTypeSize() "
                    f"switch {cpp_by_id}"))

    # -- serialized field order ----------------------------------------
    for cpp_fn, py_fn in ORDER_TWINS.items():
        cpp_body = cppscan.function_body(msg_cc, cpp_fn)
        if cpp_body is None:
            findings.append(Finding(
                PASS, MESSAGE_CC, 0, f"order:{cpp_fn}",
                f"serialize function {cpp_fn} not found in message.cc"))
            continue
        cpp_seq = cppscan.write_sequence(cpp_body)
        py_seq = _py_write_sequence(wire_ast, py_fn)
        if py_seq is None:
            findings.append(Finding(
                PASS, WIRE_PY, 0, f"order:{cpp_fn}",
                f"twin function {py_fn} not found in wire.py"))
            continue
        if cpp_seq != py_seq:
            findings.append(Finding(
                PASS, WIRE_PY, 0, f"order:{cpp_fn}",
                f"field order of {py_fn} {py_seq} disagrees with "
                f"{cpp_fn} {cpp_seq} — serialized byte layout drift"))

    # -- burst-unit delimiter position (wire v5) -----------------------
    # The atomic-burst delimiter (burst_id u32 + burst_len u32) must be
    # emitted directly after the flags byte — the third u8 of the
    # RequestList header — in BOTH twins.  The generic order check above
    # only fires when the twins disagree with *each other*; this check
    # pins the absolute position, so a "both twins moved it" regression
    # (which would silently break coordinator burst-unit ingest of v5
    # frames from older peers) is also caught.
    def _burst_delimiter_ok(seq: List[str]) -> bool:
        u8s = [i for i, op in enumerate(seq) if op == "u8"]
        return (len(u8s) >= 3
                and seq[u8s[2] + 1:u8s[2] + 3] == ["u32", "u32"])

    rl_body = cppscan.function_body(msg_cc, "SerializeRequestList")
    rl_cpp_seq = cppscan.write_sequence(rl_body) if rl_body is not None else []
    rl_py_seq = _py_write_sequence(wire_ast, "serialize_request_list") or []
    for rel, seq, label in (
            (MESSAGE_CC, rl_cpp_seq, "SerializeRequestList"),
            (WIRE_PY, rl_py_seq, "serialize_request_list")):
        if not _burst_delimiter_ok(seq):
            findings.append(Finding(
                PASS, rel, 0, "burst-delimiter",
                f"{label} does not emit the burst-unit delimiter "
                "(burst_id u32, burst_len u32) immediately after the "
                "flags byte — v5 atomic-burst framing drift"))

    # -- response-cache signature field order --------------------------
    sig_body = cppscan.function_body(ctrl_cc, "ResponseCache::Signature")
    if sig_body is None:
        findings.append(Finding(
            PASS, CONTROLLER_CC, 0, "signature-order",
            "ResponseCache::Signature not found in controller.cc"))
    else:
        cpp_fields = _signature_fields_cpp(sig_body)
        py_fields, sig_line = _signature_fields_py(wire_src, wire_ast)
        if not py_fields:
            findings.append(Finding(
                PASS, WIRE_PY, 0, "signature-order",
                "Entry.signature() not found in wire.py"))
        elif cpp_fields != py_fields:
            findings.append(Finding(
                PASS, WIRE_PY, sig_line, "signature-order",
                f"Entry.signature() field order {py_fields} disagrees "
                f"with ResponseCache::Signature {cpp_fields} — cache "
                "keys would diverge across implementations"))

    # -- message-table key separator -----------------------------------
    # Both sources spell the separator as the escape `\x01`; match the
    # raw character sequence so f-strings and char literals both count.
    if "\\x01" not in ctrl_cc:
        findings.append(Finding(
            PASS, CONTROLLER_CC, 0, "table-key-separator",
            "TableKey '\\x01' separator not found in controller.cc"))
    if "\\x01" not in fallback_src:
        findings.append(Finding(
            PASS, FALLBACK_PY, 0, "table-key-separator",
            "_table_key '\\x01' separator not found in fallback.py — "
            "table keys would diverge from the native controller"))

    return findings
