"""Graceful preemption (core/preempt.py): coordinated drain, emergency
commit, and planned elastic resize.

Unit tests drive the drain coordinator over a fake KV client (notice
intake, the commit-boundary agreement protocol, the stall-inspector
exclusion, the launcher's kill-grace knob); the acceptance smokes
launch REAL 2-process elastic jobs where the `preempt` fault action
delivers a notice to one rank and assert (a) every rank reaches the
drain commit, the departing rank exits DRAIN_EXIT_CODE, and the driver
resizes with ZERO restart-budget/blacklist strikes even under
``--max-restarts 0``, and (b) a `preempt` and a `kill` in the same job
are classified differently — only the kill charges the budget.
"""

import logging
import os
import signal
import subprocess
import sys
import time

import pytest

import horovod_tpu
from horovod_tpu.core import faults, preempt
from horovod_tpu.core.exceptions import (DrainInterrupt,
                                         HostsUpdatedInterrupt)
from horovod_tpu.core.preempt import (DRAIN_EXIT_CODE, _DrainCoordinator,
                                      configured_signal, resolve_signal)
from horovod_tpu.elastic.worker import RESET_EXIT_CODE

from test_stall import FakeKV, FakeKVNoDir

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_SCRIPT = os.path.join(_REPO, "tests", "elastic_train_script.py")


@pytest.fixture(autouse=True)
def _clean_preempt():
    yield
    preempt.uninstall()
    preempt.PENDING = False
    faults.uninstall()


def _wait_until(cond, timeout=3.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestSignals:
    def test_resolve_signal_spellings(self):
        assert resolve_signal("SIGTERM") is signal.SIGTERM
        assert resolve_signal("term") is signal.SIGTERM
        assert resolve_signal(str(int(signal.SIGUSR2))) is signal.SIGUSR2
        assert resolve_signal("") is None
        assert resolve_signal(None) is None
        assert resolve_signal("SIGNOPE") is None
        assert resolve_signal("999") is None

    def test_configured_signal_env(self, monkeypatch):
        monkeypatch.delenv("HVTPU_PREEMPT_SIGNAL", raising=False)
        assert configured_signal() is signal.SIGTERM
        monkeypatch.setenv("HVTPU_PREEMPT_SIGNAL", "USR2")
        assert configured_signal() is signal.SIGUSR2
        # unknown spelling falls back rather than disabling forwarding
        monkeypatch.setenv("HVTPU_PREEMPT_SIGNAL", "SIGNOPE")
        assert configured_signal() is signal.SIGTERM

    def test_drain_exit_code_is_distinct(self):
        assert DRAIN_EXIT_CODE != RESET_EXIT_CODE
        assert DRAIN_EXIT_CODE not in (0, 1)
        assert DRAIN_EXIT_CODE != 128 + int(signal.SIGTERM)


class TestFaultAction:
    def test_preempt_grammar_is_one_shot(self):
        cs = faults.parse_spec("worker.step:preempt@rank=1,count=3")
        assert cs[0].action == "preempt"
        assert cs[0].times == 1  # planned departures don't repeat
        assert cs[0].count == 3

    def test_unknown_action_message_names_preempt(self):
        with pytest.raises(faults.FaultSpecError, match="preempt"):
            faults.parse_spec("worker.step:explode")

    def test_preempt_action_delivers_notice(self, caplog):
        # without a coordinator installed the notice is dropped loudly,
        # not fatally — the fault path must be safe in non-elastic jobs
        faults.install("worker.step:preempt", rank=0)
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            assert faults.inject("worker.step") is False
        assert any("not installed" in r.getMessage()
                   for r in caplog.records)


class TestNoticeIntake:
    def test_notice_file_triggers_departure(self, tmp_path):
        notice = tmp_path / "preempt-notice"
        c = _DrainCoordinator(rank=0, size=1, grace_s=60.0,
                              notice_file=str(notice), generation=0,
                              client=None)
        try:
            time.sleep(0.3)
            assert not c._departing  # no file yet: nothing pending
            notice.write_text("going away\n")
            _wait_until(lambda: c._departing, msg="file notice")
            assert preempt.PENDING is True
            assert c._reason == "file"
            assert 0 in c.draining_ranks()
        finally:
            c.stop()

    def test_notice_is_idempotent_and_keeps_first_reason(self):
        c = _DrainCoordinator(rank=0, size=1, grace_s=60.0,
                              notice_file=None, generation=0,
                              client=None)
        try:
            c.notice("api")
            c.notice("signal")
            assert c._reason == "api"
        finally:
            c.stop()

    def test_grace_remaining_counts_down_and_expires(self):
        c = _DrainCoordinator(rank=0, size=1, grace_s=0.5,
                              notice_file=None, generation=0,
                              client=None)
        try:
            # window open: reported as draining...
            c._departing = True
            c._notice_t = time.monotonic()
            rem = c.draining_ranks()
            assert 0 in rem and 0 < rem[0] <= 0.5
            # ...window past: exclusion expires, normal stall semantics
            c._notice_t = time.monotonic() - 1.0
            assert c.draining_ranks() == {}
        finally:
            c.stop()


@pytest.fixture(params=[FakeKV, FakeKVNoDir],
                ids=["dir-get", "try-get-fallback"])
def kv(request):
    return request.param()


class TestDrainProtocol:
    """Two coordinators over one fake KV: the full notice → plan →
    agreed-boundary exchange, exactly as two ranks would run it."""

    def _pair(self, kv):
        a = _DrainCoordinator(rank=0, size=2, grace_s=60.0,
                              notice_file=None, generation=0, client=kv)
        b = _DrainCoordinator(rank=1, size=2, grace_s=60.0,
                              notice_file=None, generation=0, client=kv)
        return a, b

    def test_peer_observes_notice_and_plan(self, kv):
        a, b = self._pair(kv)
        try:
            a.notice("api")
            # the watcher publishes, the peer's watcher observes
            _wait_until(lambda: 0 in b.draining_ranks(),
                        msg="peer notice observation")
            assert preempt.PENDING is True
            # departing rank's first boundary: publish plan = count+1,
            # do NOT drain yet (peers need a step to learn the plan)
            assert a.drain_boundary(5) is False
            _wait_until(
                lambda: b.drain_boundary(5) is False and b._plans,
                msg="peer plan observation")
            # the agreed boundary: both sides say drain NOW
            assert a.drain_boundary(6) is True
            assert b.drain_boundary(6) is True
            # the peer completes by raising DrainInterrupt (a
            # HostsUpdatedInterrupt: the committed state stands)
            with pytest.raises(DrainInterrupt) as ei:
                b.finish_drain(6)
            assert isinstance(ei.value, HostsUpdatedInterrupt)
            assert ei.value.rank == 0
            # finish_drain is once-only; later boundaries are inert
            assert b.drain_boundary(7) is False
        finally:
            a.stop()
            b.stop()

    def test_generation_namespacing(self, kv):
        """A relaunched world (new generation) must never observe the
        previous incarnation's drain markers."""
        a = _DrainCoordinator(rank=0, size=2, grace_s=60.0,
                              notice_file=None, generation=0, client=kv)
        b = _DrainCoordinator(rank=1, size=2, grace_s=60.0,
                              notice_file=None, generation=1, client=kv)
        try:
            a.notice("api")
            _wait_until(lambda: kv.key_value_dir_get is None
                        or any("notice/0" in k for k, _ in
                               kv.key_value_dir_get("hvtdrain/0/")),
                        msg="notice published")
            time.sleep(0.5)  # several polls on b's side
            assert b.draining_ranks() == {}
            assert b.drain_boundary(5) is False
        finally:
            a.stop()
            b.stop()

    def test_debug_state_surfaces_protocol(self, kv):
        a, b = self._pair(kv)
        try:
            a.notice("api")
            a.drain_boundary(3)
            d = a.debug_state()
            assert d["departing"] is True and d["reason"] == "api"
            assert d["plans"] == {"0": 4}
            _wait_until(lambda: b.debug_state()["draining_ranks"],
                        msg="peer debug state")
            assert b.debug_state()["departing"] is False
        finally:
            a.stop()
            b.stop()


class TestStallExclusion:
    """A draining rank is reported, not blamed: no stall abort fires
    for it during the grace window."""

    def test_strict_rendezvous_holds_abort_for_draining_rank(
            self, monkeypatch, caplog):
        from horovod_tpu.comm.stall import SyncStallInspector

        monkeypatch.setattr(preempt, "PENDING", True)
        monkeypatch.setattr(preempt, "draining_ranks",
                            lambda: {1: 25.0})
        kv = FakeKV()
        insp = SyncStallInspector(kv, rank=0, warn_s=0.05, abort_s=0.15,
                                  generation=1)

        def late_peer():
            time.sleep(0.5)  # well past abort_s
            kv.key_value_set("hvtstall/1/0/0/1", "op")

        import threading

        t = threading.Thread(target=late_peer)
        t.start()
        with caplog.at_level(logging.INFO, logger="horovod_tpu"):
            insp.rendezvous(0, [0, 1], "op")  # must NOT raise
        t.join()
        held = [r for r in caplog.records
                if "draining" in r.getMessage()]
        assert held and "rank 1" in held[0].getMessage()

    def test_strict_rendezvous_still_aborts_non_draining_rank(
            self, monkeypatch):
        from horovod_tpu.comm.stall import SyncStallInspector
        from horovod_tpu.core.exceptions import HorovodInternalError

        monkeypatch.setattr(preempt, "PENDING", True)
        monkeypatch.setattr(preempt, "draining_ranks",
                            lambda: {2: 25.0})  # rank 2, not rank 1
        insp = SyncStallInspector(FakeKV(), rank=0, warn_s=0.05,
                                  abort_s=0.15, generation=1)
        with pytest.raises(HorovodInternalError, match=r"\[1\]"):
            insp.rendezvous(0, [0, 1], "op")

    def test_amortized_evaluate_holds_abort_for_draining_rank(
            self, monkeypatch, caplog):
        from test_stall import _NeverReady

        from horovod_tpu.comm.stall import AmortizedStallInspector

        monkeypatch.setattr(preempt, "PENDING", True)
        monkeypatch.setattr(preempt, "draining_ranks",
                            lambda: {1: 25.0})
        insp = AmortizedStallInspector(
            FakeKV(), rank=0, warn_s=0.05, abort_s=0.1,
            heartbeat_s=30.0, generation=1)  # beat never fires
        try:
            insp.pre_op(0, [0, 1], "allreduce:x")
            time.sleep(0.2)  # past abort_s
            with caplog.at_level(logging.INFO, logger="horovod_tpu"):
                insp._evaluate(peers={})
            assert insp.failure is None  # held, not aborted
            assert any("draining" in r.getMessage()
                       for r in caplog.records)
            # once the window expires the hold lifts
            monkeypatch.setattr(preempt, "draining_ranks", lambda: {})
            insp._evaluate(peers={})
            assert insp.failure and "[1]" in insp.failure
        finally:
            insp.stop()


class TestTermGrace:
    def test_term_grace_knob(self, monkeypatch):
        from horovod_tpu.runner import safe_shell_exec as sse

        monkeypatch.delenv("HVTPU_TERM_GRACE_SECONDS", raising=False)
        assert sse.term_grace_s() == sse.GRACEFUL_TERMINATION_TIME_S
        monkeypatch.setenv("HVTPU_TERM_GRACE_SECONDS", "45")
        assert sse.term_grace_s() == 45.0
        for bad in ("nope", "-1", "0"):
            monkeypatch.setenv("HVTPU_TERM_GRACE_SECONDS", bad)
            assert sse.term_grace_s() == sse.GRACEFUL_TERMINATION_TIME_S

    def test_launcher_flags_thread_drain_env(self):
        from horovod_tpu.runner.launch import parse_args

        args = parse_args([
            "-np", "2", "--drain-grace", "12.5",
            "--preempt-notice-file", "/tmp/notice",
            "--", "python", "train.py"])
        assert args.drain_grace == 12.5
        assert args.preempt_notice_file == "/tmp/notice"


# ---------------------------------------------------------------------------
# acceptance: real 2-process elastic runs under an injected preemption
# ---------------------------------------------------------------------------


def _launch_elastic(tmp_path, fault_spec, extra_args=(), epochs=6,
                    timeout=300):
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    # one full watcher poll (0.2s) fits inside a step, so the K=1
    # plan lookahead always reaches peers before the agreed boundary
    env["EPOCH_SLEEP"] = "0.3"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--fault-spec", fault_spec,
        *extra_args,
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=timeout,
                         capture_output=True, text=True)
    return res, res.stdout + res.stderr


@pytest.mark.multiprocess
@pytest.mark.slow
def test_preempt_drains_and_resizes_without_budget_strike(tmp_path):
    """ISSUE-8 acceptance: rank 1 gets a preemption notice at its 3rd
    step.  All ranks must reach the drain commit, rank 1 must exit
    DRAIN_EXIT_CODE, and the driver must resize WITHOUT a restart-
    budget strike — proven by --max-restarts 0, under which any
    budget-charged relaunch would fail the job.  The next incarnation
    resumes from the drain commit: every epoch appears exactly once
    (zero lost steps)."""
    res, out = _launch_elastic(
        tmp_path, "worker.step:preempt@rank=1,count=3",
        extra_args=("--max-restarts", "0"))
    assert res.returncode == 0, out[-4000:]
    # the departing rank announced the planned exit...
    assert "exiting 79 for a planned departure" in out, out[-4000:]
    # ...and the driver classified it as such (no strike, no blacklist)
    assert "planned departure" in out, out[-4000:]
    assert "restart budget exhausted" not in out, out[-4000:]
    # exactly one resize: launch, drain, relaunch
    assert out.count("launching 2 workers") == 2, out[-4000:]
    assert "DONE size=2 epoch=6" in out, out[-4000:]
    # zero lost steps: the next incarnation resumed from the drain
    # commit, so no epoch was re-run and none was skipped — and no
    # rank fell back to the collective-failure (rollback) path
    epochs = [int(line.split("epoch=")[1].split()[0])
              for line in out.splitlines()
              if line.split(":", 1)[-1].lstrip().startswith("EPOCH ")]
    assert epochs == list(range(6)), (epochs, out[-4000:])
    assert "collective failure" not in out, out[-4000:]


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow
def test_preempt_with_predicted_cycles_in_flight(tmp_path):
    """Satellite (ISSUE 11): a preemption drain arriving while the
    eager controller is running PREDICTED cycles (on by default) must
    still reach a clean emergency commit: the drain-commit quiesce
    waits for in-flight confirmations (or rolls the predictor back to
    full negotiation), so no unconfirmed schedule's results are
    persisted.  Asserts the planned departure, the resumed epochs, and
    that prediction actually engaged."""
    script = os.path.join(_REPO, "tests", "predict_drain_script.py")
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # enough steady same-shape epochs BEFORE the notice (count=8) for
    # prediction to verify its bit-sets and engage, so the drain really
    # does land with predicted cycles in flight
    env["ELASTIC_EPOCHS"] = "14"
    env["EPOCH_SLEEP"] = "0.3"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--fault-spec", "worker.step:preempt@rank=1,count=8",
        "--max-restarts", "0",
        "--", sys.executable, script,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=300,
                         capture_output=True, text=True)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "exiting 79 for a planned departure" in out, out[-4000:]
    assert "restart budget exhausted" not in out, out[-4000:]
    done = [l for l in out.splitlines() if "DONE size=" in l]
    assert done, out[-4000:]
    # prediction engaged before/after the drain and every mispredict
    # (if any) was recovered — the run completed with correct sums
    assert "epoch=14" in done[-1], done[-1]
    pred = float(done[-1].split("predicted=")[1].split()[0])
    assert pred > 0, done[-1]


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow
def test_drain_vs_kill_classification(tmp_path):
    """Chaos matrix: a `kill` and a `preempt` in the same job must be
    classified differently.  Rank 0 is killed at its 2nd step of
    incarnation 1 (charges the ONLY budgeted restart); rank 1 is
    preempted in incarnation 2 (drains, charges nothing).  Under
    --max-restarts 1 the job completes ONLY if the drain was free."""
    res, out = _launch_elastic(
        tmp_path,
        "worker.step:kill@rank=0,count=2;"
        "worker.step:preempt@rank=1,count=3",
        extra_args=("--max-restarts", "1"), epochs=8)
    assert res.returncode == 0, out[-4000:]
    # the kill took a crash strike...
    assert "fault injection: killing rank 0" in out, out[-4000:]
    assert "strikes)" in out, out[-4000:]
    # ...the drain did not
    assert "exiting 79 for a planned departure" in out, out[-4000:]
    assert "planned departure" in out, out[-4000:]
    assert "restart budget exhausted" not in out, out[-4000:]
    # three incarnations: start, post-kill relaunch, post-drain resize
    assert out.count("launching 2 workers") == 3, out[-4000:]
    assert "DONE size=2 epoch=8" in out, out[-4000:]
