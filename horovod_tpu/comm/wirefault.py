"""Wire-plane fault tolerance: consensus abort-and-retry + link health.

Every other plane is hardened — storage commits are crash-consistent,
the coordination KV is generation-fenced, the fleet front door
survives overload — but the wire plane that actually moves gradients
was fail-stop: one lossy link drove the stall watchdog's
warn→abort→poison path into a full elastic restart, discarding every
in-flight step ("Demystifying NCCL", PAPERS.md, documents exactly this
gap in production collective stacks).  This module implements the
first two rungs of the degradation ladder (docs/robustness.md):

**Rung 1 — consensus abort-and-retry** (:class:`WireConsensus`).
A collective ``(set_id, seq)`` that fails with a transport-shaped
error is not immediately job-fatal: the failing rank posts an abort
VOTE for attempt *k* under ``hvtwire/<gen>/<set>/<seq>/<k>/<rank>`` on
the fenced coordination KV, then waits for the member ranks to agree
attempt *k* is dead before anyone reissues attempt *k+1* under
attempt-tagged wire keys (``native/wire.py::attempt_tag``).  The
agreement has exactly three outcomes, chosen so every collective
delivers **exactly one result or none** — never a torn mix of
attempts:

- ``RETRY`` — every member voted failed.  Nobody holds a result of
  attempt *k*, so all members reissue attempt *k+1*.
- ``LATE_JOIN`` — this rank (and every other voter) failed BEFORE
  dispatch put bytes on the wire, and every non-voting member is
  observably parked *inside* attempt *k* (its stall-heartbeat
  snapshot shows the same in-flight descriptor at the same sequence
  number).  Re-dispatching attempt *k* completes the wedged peers'
  pending collective — they never learn anything happened.  The
  late-joiner retracts its vote first (``rejoin``), so a peer that
  fails afterwards can never see "all voted" and tear off into
  attempt *k+1*.
- ``ESCALATE`` — any member already COMPLETED attempt *k* (retrying
  would deliver two different attempts), a mid-flight failure mixed
  with rejoined peers, or the consensus deadline expired.  The error
  surfaces exactly as before this module existed:
  ``HorovodInternalError`` → elastic reset (rung 3).

**Rung 2 — link-health route-around** (:class:`LinkHealth`).
Per-peer EWMA latency/loss scores folded out of the stall inspector's
existing heartbeat stream.  Past a degradation threshold
(``HVTPU_LINK_DEGRADED_SCORE``), :meth:`LinkHealth.ring_order`
re-orders the ring permutation to demote the sick rank to the ring
tail — the compositional path-selection idea of HiCCL (PAPERS.md) —
before anything escalates to an elastic reset.  On the XLA data plane
the order is advisory (XLA owns the ring schedule); the fabric
simulator's ring exchange rewires for real (sim/scenarios.py
``lossy-link``).

Retries are OFF by default (``HVTPU_WIRE_RETRIES=0``): the failure
semantics of existing jobs are unchanged until a deployment opts in.
All timing goes through ``core/clock.py``, so the whole protocol runs
unmodified on the fabric simulator's virtual time.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import clock
from ..obs import flight
from ..obs import metrics as obs_metrics

logger = logging.getLogger("horovod_tpu")

# Recovery telemetry (catalog in docs/observability.md).
_M_RETRIES = obs_metrics.counter(
    "hvtpu_collective_retries_total",
    "Collective attempts reissued (or late-joined) after a consensus "
    "abort agreed the previous attempt was dead.")
_M_CONSENSUS_S = obs_metrics.histogram(
    "hvtpu_collective_abort_consensus_seconds",
    "Time from posting an abort vote for a failed collective attempt "
    "to the agreed decision (retry / late-join / escalate).")
_M_LINK_HEALTH = obs_metrics.gauge(
    "hvtpu_link_health",
    "Worst per-peer wire-link degradation score (0 = healthy, "
    "1 = dead), from heartbeat-derived EWMA latency/loss.")
_M_REROUTES = obs_metrics.counter(
    "hvtpu_ring_reroutes_total",
    "Ring-permutation reroutes taken to avoid a degraded link before "
    "escalating to an elastic reset.")

_NS = "hvtwire"  # abort-consensus vote namespace on the fenced KV

#: Consensus outcomes.
RETRY = "retry"
LATE_JOIN = "late_join"
ESCALATE = "escalate"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None


def retry_limit() -> int:
    """Max reissue attempts per collective (``HVTPU_WIRE_RETRIES``,
    default 0 = the pre-existing fail-fast behavior)."""
    return int(_env_float("HVTPU_WIRE_RETRIES", 0))


def retry_backoff_s() -> float:
    """Base backoff between attempts (``HVTPU_WIRE_RETRY_BACKOFF_S``);
    attempt k sleeps k times this before reissuing."""
    return _env_float("HVTPU_WIRE_RETRY_BACKOFF_S", 0.05)


def consensus_deadline_s() -> float:
    """How long a failed rank waits for the member ranks to agree an
    attempt is dead before escalating (``HVTPU_WIRE_CONSENSUS_S``)."""
    return _env_float("HVTPU_WIRE_CONSENSUS_S", 5.0)


def record_retry(rank: int, set_id, seq: int, attempt: int,
                 decision: str) -> None:
    """Count a consensus-approved reissue (RETRY or LATE_JOIN) and
    leave the audit trail."""
    _M_RETRIES.inc()
    logger.warning(
        "collective (set %s, op #%s) attempt %d agreed dead by "
        "consensus: %s", set_id, seq, attempt, decision)
    if flight.ACTIVE:
        flight.note("collective_retry", rank=rank, process_set=set_id,
                    op_seq=seq, attempt=attempt, decision=decision)


class AttemptFailed(Exception):
    """One collective attempt failed with a transport-shaped error.

    ``predispatch`` is True when the failure provably happened BEFORE
    this rank put any bytes on the wire (an injected ``wire.send``
    drop, a refused connection) — the only class that may LATE_JOIN a
    still-pending attempt.  ``cause`` is the original backend error.
    """

    def __init__(self, predispatch: bool, cause: BaseException):
        super().__init__(str(cause))
        self.predispatch = predispatch
        self.cause = cause


class WireConsensus:
    """Abort-and-retry agreement for one rank's failed collectives.

    One instance per (KV client, rank, generation); the KV is expected
    to be the FENCED client the stall inspector already holds, so a
    superseded zombie's votes are invisible to live readers.  Peer
    classification reads the stall inspector's existing heartbeat
    snapshots (``hb_prefix``) — the protocol adds KV traffic only when
    a collective actually fails.
    """

    def __init__(self, kv, rank: int, generation: int = 0,
                 hb_prefix: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        self._kv = kv
        self.rank = rank
        self.gen = generation
        self._hb_prefix = hb_prefix
        self.deadline_s = (consensus_deadline_s()
                           if deadline_s is None else deadline_s)

    # -- keys ----------------------------------------------------------
    def _key(self, set_id, seq: int, attempt: int, rank: int) -> str:
        return f"{_NS}/{self.gen}/{set_id}/{seq}/{attempt}/{rank}"

    def _post(self, key: str, value: str) -> bool:
        """Write a vote, replacing any previous value.

        The coordination service forbids overwriting a live key, so a
        retraction (and a re-vote after a failed late-join re-entry)
        must delete-then-set.  The gap where neither value is visible
        is safe: a peer that reads during it sees a missing vote and
        falls back to heartbeat classification, which at worst
        ESCALATEs — never licenses a torn retry.
        """
        try:
            self._kv.key_value_set(key, value)
            return True
        except Exception:
            pass
        try:
            self._kv.key_value_delete(key)
            self._kv.key_value_set(key, value)
            return True
        except Exception:
            return False

    def _votes(self, set_id, seq: int, attempt: int,
               ranks: Sequence[int]) -> Dict[int, dict]:
        prefix = f"{_NS}/{self.gen}/{set_id}/{seq}/{attempt}/"
        dir_get = getattr(self._kv, "key_value_dir_get", None)
        out: Dict[int, dict] = {}
        if dir_get is not None:
            try:
                for k, v in dir_get(prefix):
                    try:
                        out[int(k.rsplit("/", 1)[-1])] = json.loads(v)
                    except (ValueError, TypeError):
                        continue
                return out
            except Exception:
                out = {}
        for r in ranks:
            try:
                val = self._kv.key_value_try_get(
                    self._key(set_id, seq, attempt, r))
            except Exception:
                val = None
            if val is not None:
                try:
                    out[r] = json.loads(val)
                except (ValueError, TypeError):
                    continue
        return out

    # -- peer classification from heartbeat snapshots ------------------
    def _peer_states(self, set_id, seq: int, desc: str,
                     ranks: Sequence[int]) -> Dict[int, str]:
        """``waiting`` (parked inside this op), ``done`` (completed it
        and moved on — or exited), or ``unknown`` (no usable snapshot
        yet / still lagging behind the op)."""
        states = {r: "unknown" for r in ranks}
        if not self._hb_prefix:
            return states
        dir_get = getattr(self._kv, "key_value_dir_get", None)
        if dir_get is None:
            return states
        try:
            entries = dir_get(self._hb_prefix)
        except Exception:
            return states
        latest: Dict[int, Tuple[int, str]] = {}
        for k, v in entries:
            parts = k.rsplit("/", 2)
            if len(parts) < 3:
                continue
            try:
                r, b = int(parts[-2]), int(parts[-1])
            except ValueError:
                continue
            if r in states and (r not in latest or b > latest[r][0]):
                latest[r] = (b, v)
        for r, (_b, v) in latest.items():
            try:
                snap = json.loads(v)
            except Exception:
                continue
            if snap.get("bye") or snap.get("fail"):
                # exited or already failing: retrying cannot help
                states[r] = "done"
                continue
            pset = snap.get("sets", {}).get(str(set_id))
            if not pset:
                continue
            pseq = int(pset.get("seq", 0))
            if pseq <= seq:
                continue  # not at this op yet — keep polling
            if pseq == seq + 1 and pset.get("inflight") == desc:
                states[r] = "waiting"
            else:
                states[r] = "done"
        return states

    # -- the agreement -------------------------------------------------
    def vote_and_decide(self, set_id, seq: int, attempt: int,
                        members: Sequence[int], desc: str,
                        predispatch: bool) -> str:
        """Post this rank's abort vote for (set, seq, attempt) and
        block until the outcome is decidable; returns ``RETRY``,
        ``LATE_JOIN`` or ``ESCALATE`` (see module docstring for the
        exactly-once argument)."""
        t0 = clock.monotonic()
        mine = {"st": "pre" if predispatch else "mid", "d": desc}
        if not self._post(self._key(set_id, seq, attempt, self.rank),
                          json.dumps(mine)):
            # can't even reach the KV: nothing to agree over
            return ESCALATE
        others = [r for r in members if r != self.rank]
        deadline = t0 + self.deadline_s
        decision = ESCALATE
        sleep = 0.0
        while True:
            votes = self._votes(set_id, seq, attempt, others)
            missing = [r for r in others if r not in votes]
            pure = all(v.get("st") in ("pre", "rejoin")
                       for v in votes.values()) and predispatch
            if not missing:
                if any(v.get("st") == "rejoin" for v in votes.values()):
                    # someone is back INSIDE attempt k: join it or die
                    decision = LATE_JOIN if pure else ESCALATE
                else:
                    # every member agreed attempt k is dead; nobody
                    # holds its result — all reissue attempt k+1
                    decision = RETRY
                break
            states = self._peer_states(set_id, seq, desc, missing)
            if any(states[r] == "done" for r in missing):
                # a peer completed attempt k while we failed it: a
                # retry would deliver a second, different attempt
                decision = ESCALATE
                break
            if pure and all(states[r] == "waiting" for r in missing):
                decision = LATE_JOIN
                break
            if clock.monotonic() >= deadline:
                decision = ESCALATE
                break
            sleep = min(0.05, sleep * 2 if sleep else 0.002)
            clock.sleep(sleep)
        if decision == LATE_JOIN:
            # Retract the failure vote BEFORE re-entering attempt k: a
            # member that fails after this must see this rank as back
            # inside the attempt (rejoin), never as a completed vote
            # set that licenses attempt k+1 while we wedge in k.
            if not self._post(self._key(set_id, seq, attempt, self.rank),
                              json.dumps({"st": "rejoin", "d": desc})):
                decision = ESCALATE
        waited = clock.monotonic() - t0
        _M_CONSENSUS_S.observe(waited)
        if flight.ACTIVE:
            flight.note("collective_abort_consensus", rank=self.rank,
                        process_set=set_id, op_seq=seq, attempt=attempt,
                        decision=decision, waited_s=round(waited, 6))
        return decision

    def cleanup(self, set_id, seq: int, attempts: int) -> None:
        """Drop this rank's own votes for a delivered collective (each
        rank deletes only its own keys; best-effort)."""
        for a in range(attempts + 1):
            try:
                self._kv.key_value_delete(
                    self._key(set_id, seq, a, self.rank))
            except Exception:
                pass


class LinkHealth:
    """Per-peer wire-link scores from heartbeat arrival gaps.

    ``observe`` is fed by the stall inspector's beat loop: a beat that
    arrives after ``gap_s`` updates the latency EWMA (as a ratio of
    the expected cadence), a skipped/overdue beat counts as a loss.
    ``score`` folds both into [0, 1]; past ``degraded_score`` the peer
    is considered to sit behind a sick link and :meth:`ring_order`
    demotes it to the ring tail (counting a reroute when the order
    actually changes).  Thread-safe: the beat thread writes, the data
    plane and /debug read.
    """

    def __init__(self, expect_s: float, alpha: float = 0.25,
                 degraded_score: Optional[float] = None):
        self.expect_s = max(float(expect_s), 1e-6)
        self.alpha = alpha
        self.degraded_score = (
            _env_float("HVTPU_LINK_DEGRADED_SCORE", 0.5)
            if degraded_score is None else degraded_score)
        self._lock = threading.Lock()
        self._lat: Dict[int, float] = {}    # EWMA gap/expected ratio
        self._loss: Dict[int, float] = {}   # EWMA loss indicator
        self._last_order: Dict[tuple, tuple] = {}

    def observe(self, peer: int, gap_s: Optional[float] = None,
                lost: bool = False) -> None:
        a = self.alpha
        with self._lock:
            if lost:
                prev = self._loss.get(peer, 0.0)
                self._loss[peer] = prev + a * (1.0 - prev)
            else:
                prev = self._loss.get(peer, 0.0)
                self._loss[peer] = prev * (1.0 - a)
                if gap_s is not None:
                    ratio = max(0.0, gap_s) / self.expect_s
                    prevl = self._lat.get(peer, 1.0)
                    self._lat[peer] = prevl + a * (ratio - prevl)

    def _score_locked(self, peer: int) -> float:
        loss = self._loss.get(peer, 0.0)
        lat = self._lat.get(peer, 1.0)
        # latency starts penalizing at 2x the expected cadence and
        # saturates at 10x; loss dominates (a flapping link loses
        # beats long before it slows them)
        lat_pen = min(1.0, max(0.0, (lat - 2.0) / 8.0))
        return min(1.0, loss + 0.5 * lat_pen)

    def score(self, peer: int) -> float:
        with self._lock:
            return self._score_locked(peer)

    def worst(self) -> float:
        with self._lock:
            peers = set(self._lat) | set(self._loss)
            return max((self._score_locked(r) for r in peers),
                       default=0.0)

    def degraded(self) -> List[int]:
        with self._lock:
            peers = sorted(set(self._lat) | set(self._loss))
            return [r for r in peers
                    if self._score_locked(r) >= self.degraded_score]

    def publish(self) -> None:
        """Export the worst score to the ``hvtpu_link_health`` gauge."""
        _M_LINK_HEALTH.set(self.worst())

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            peers = sorted(set(self._lat) | set(self._loss))
            return {str(r): {
                "score": round(self._score_locked(r), 4),
                "lat_ratio": round(self._lat.get(r, 1.0), 4),
                "loss": round(self._loss.get(r, 0.0), 4),
            } for r in peers}

    def ring_order(self, members: Sequence[int]) -> List[int]:
        """``members`` re-ordered so degraded peers sit at the ring
        tail (healthiest first among the sick; relative order of
        healthy members preserved).  Counts a reroute + flight event
        when the order for this member set actually changes."""
        with self._lock:
            scored = [(self._score_locked(r), i, r)
                      for i, r in enumerate(members)]
            healthy = [r for s, _i, r in scored
                       if s < self.degraded_score]
            sick = [r for s, _i, r in sorted(scored)
                    if s >= self.degraded_score]
            order = healthy + sick
            key = tuple(sorted(members))
            prev = self._last_order.get(key)
            changed = prev is not None and prev != tuple(order)
            self._last_order[key] = tuple(order)
        if changed:
            _M_REROUTES.inc()
            logger.warning(
                "wire link degraded: ring rerouted to demote ranks %s "
                "to the tail", sick)
            if flight.ACTIVE:
                flight.note("ring_reroute", demoted=list(sick),
                            order=list(order))
        return order
