"""Deterministic virtual-time kernel for the fabric simulator.

The simulator's job (ROADMAP: robustness) is to run the REAL control
plane — eager negotiation, drain coordination, rendezvous audits,
heartbeats — at 256–4096 virtual ranks inside one process, under
chaos, deterministically.  The kernel provides the substrate:

- **Virtual time.**  :class:`VirtualClock` implements the
  ``core/clock.py`` seam; every ``clock.monotonic()`` /
  ``clock.sleep()`` / ``clock.call_later()`` issued by framework code
  on a simulated thread reads or advances the kernel's discrete-event
  clock instead of the host's.  A scenario covering ten minutes of
  drain grace runs in wall-clock milliseconds, and two runs with the
  same seed produce byte-identical event logs.

- **Cooperative rank tasks on real threads.**  Framework code is full
  of genuine blocking calls (KV blocking gets, retry backoff sleeps,
  burst-gate waits), so each virtual rank runs on a real OS thread —
  but the kernel holds a single *run token*: exactly one task thread
  executes at any instant, and control passes task → scheduler →
  task only at virtual-time events.  That serialisation is what makes
  the simulation deterministic without rewriting the framework into
  coroutines.

- **Events.**  A heap of ``(virtual_time, seq, callback)`` entries.
  ``seq`` (a monotonically increasing tie-breaker) makes simultaneous
  events fire in scheduling order, which is itself deterministic.

- **Wait tokens.**  The primitive the in-memory KV fabric builds
  blocking-get-with-timeout from: a task parks on a token
  (:meth:`SimKernel.block`), any other task or timer resolves it
  (:meth:`SimKernel.notify`), and an armed timeout event resolves it
  the other way.  Each park uses a FRESH token, so a stale timeout
  event can never wake a later wait.

- **Deadlock detection.**  When the event heap drains while tasks are
  still parked, no future event can ever wake them: the kernel raises
  :class:`DeadlockError` listing every parked task and what it is
  blocked on — turning a hung protocol into a diagnosis.

- **Virtual process exit.**  ``exit_fn`` seams in core/faults.py and
  core/preempt.py raise :class:`VirtualExit` (a BaseException, so it
  cannot be swallowed by ``except Exception`` recovery paths) to make
  one virtual rank "die" with an exit code — kill faults and planned
  drain departures — without taking the host process down.

Purity contract: nothing in this package reads the host clock or the
module-level ``random`` functions (enforced by hvtpulint's
``sim-purity`` pass); all randomness flows from :meth:`SimKernel.rng`
streams keyed by ``(seed, name)``.
"""

from __future__ import annotations

import heapq
import json
import os
import random
import threading
from typing import Any, Callable, Dict, List, Optional

from ..core import clock as core_clock

__all__ = [
    "DeadlockError",
    "SimKernel",
    "SimTimeBudgetExceeded",
    "VirtualClock",
    "VirtualExit",
    "WaitToken",
]

#: Exit code used when the kernel force-unwinds still-parked tasks at
#: teardown (distinct from any real exit code the protocols use).
ABORTED_EXIT = -1


class VirtualExit(BaseException):
    """One virtual rank leaving with an exit code (kill fault, planned
    drain departure, kernel teardown).  BaseException so framework
    ``except Exception`` recovery paths cannot swallow a death."""

    def __init__(self, code: int):
        super().__init__(f"virtual exit {code}")
        self.code = code


class DeadlockError(RuntimeError):
    """Event heap drained while tasks are still parked — no future
    event can wake them.  The message lists each parked task and its
    blocked reason."""


class SimTimeBudgetExceeded(RuntimeError):
    """Virtual time passed the scenario's budget — the protocol under
    test is livelocked or pathologically slow, not merely busy."""


class WaitToken:
    """One park of one task.  States: waiting → notified | timeout.
    Created fresh per wait so stale timeout events are inert."""

    __slots__ = ("state", "task", "value", "timer")

    def __init__(self):
        self.state = "waiting"
        self.task: Optional["_Task"] = None
        self.value: Any = None
        self.timer: Optional["_VTimer"] = None


class _VTimer:
    """Virtual ``clock.Timer``: a cancellable one-shot callback on the
    event heap (fires on the scheduler thread)."""

    __slots__ = ("_fn", "_cancelled")

    def __init__(self, fn: Callable[[], None]):
        self._fn = fn
        self._cancelled = False

    def cancel(self) -> None:
        self._cancelled = True

    def _fire(self) -> None:
        if not self._cancelled:
            self._fn()


class VirtualClock(core_clock.Clock):
    """The ``core/clock.py`` seam over the kernel: monotonic == virtual
    seconds since run start, wall == a fixed epoch plus virtual time
    (so wall-clock deltas are virtual too and logs stay reproducible),
    sleep parks the calling task, call_later lands on the event heap."""

    #: Fixed virtual wall epoch (2020-01-01T00:00:00Z).  Arbitrary but
    #: constant: wall() must never leak host time into event logs.
    EPOCH = 1577836800.0

    def __init__(self, kernel: "SimKernel"):
        self._kernel = kernel

    def monotonic(self) -> float:
        return self._kernel.now

    def wall(self) -> float:
        return self.EPOCH + self._kernel.now

    def sleep(self, seconds: float) -> None:
        self._kernel.sleep(seconds)

    def call_later(self, delay_s: float,
                   fn: Callable[[], None]) -> _VTimer:
        return self._kernel.call_later(delay_s, fn)


class _Task:
    """One virtual rank (or auxiliary actor): a real daemon thread that
    only ever runs while it holds the kernel's run token."""

    def __init__(self, kernel: "SimKernel", name: str,
                 fn: Callable[[], Any]):
        self.kernel = kernel
        self.name = name
        self.fn = fn
        self.go = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.done = False
        self.exit_code: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.result: Any = None
        self.blocked_reason = "never started"
        self._abort = False

    # -- scheduler side -------------------------------------------------
    def _resume(self) -> None:
        """Hand the run token to this task until it parks or finishes.
        Runs on the scheduler thread as an event callback."""
        if self.done:
            return
        kernel = self.kernel
        if self.thread is None:
            self.thread = kernel._start_thread(self)
        else:
            self.go.set()
        kernel._control.wait()
        kernel._control.clear()

    # -- task side ------------------------------------------------------
    def _run(self) -> None:
        kernel = self.kernel
        kernel._tls.task = self
        core_clock.install(kernel.clock)
        try:
            self.result = self.fn()
        except VirtualExit as e:
            self.exit_code = e.code
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised by run()
            self.error = e
            kernel._failed.append(self)
        finally:
            self.done = True
            core_clock.install(None)
            kernel._control.set()

    def _park(self, reason: str) -> None:
        """Give the run token back and wait to be resumed.  Must be
        called on this task's own thread."""
        self.blocked_reason = reason
        kernel = self.kernel
        kernel._control.set()
        self.go.wait()
        self.go.clear()
        self.blocked_reason = "running"
        if self._abort:
            raise VirtualExit(ABORTED_EXIT)


class SimKernel:
    """The discrete-event scheduler: owns virtual time, the event heap,
    the task set, seeded RNG streams, and the event log."""

    def __init__(self, seed: int = 0, *, stack_kb: Optional[int] = None):
        self.seed = int(seed)
        self.now = 0.0
        self.clock = VirtualClock(self)
        self._heap: List[tuple] = []  # (time, seq, fn)
        self._seq = 0
        self._control = threading.Event()
        self._tls = threading.local()
        self._tasks: List[_Task] = []
        # tasks that died with an error, appended task-side: run()'s
        # dispatch loop checks this O(1) per event instead of scanning
        # the whole task list (O(ranks) per event is a 10x slowdown at
        # 1024 vranks)
        self._failed: List[_Task] = []
        self._rngs: Dict[str, random.Random] = {}
        self.events: List[dict] = []
        self._running = False
        # 4096 rank threads at the default (often 8 MB) stack would
        # reserve absurd address space; framework control-plane frames
        # are shallow, so a small fixed stack is plenty.
        if stack_kb is None:
            stack_kb = int(os.environ.get("HVTPU_SIM_STACK_KB", "1024"))
        self._stack_bytes = max(64, int(stack_kb)) * 1024

    # -- rng / log ------------------------------------------------------
    def rng(self, name: str) -> random.Random:
        """A named deterministic RNG stream: same (seed, name) ⇒ same
        sequence, independent across names."""
        r = self._rngs.get(name)
        if r is None:
            r = random.Random(f"{self.seed}/{name}")
            self._rngs[name] = r
        return r

    def log(self, kind: str, **fields: Any) -> None:
        """Append one event-log record stamped with virtual time.
        Records must hold only virtual-time/deterministic values — the
        log is the byte-identical replay artifact."""
        rec = {"t": round(self.now, 9), "kind": kind}
        rec.update(fields)
        self.events.append(rec)

    def dump_events(self) -> str:
        """The canonical JSONL serialisation (sorted keys: dict order
        can never leak into the replay artifact)."""
        return "".join(
            json.dumps(rec, sort_keys=True) + "\n" for rec in self.events)

    # -- tasks / events -------------------------------------------------
    def spawn(self, name: str, fn: Callable[[], Any],
              delay_s: float = 0.0) -> _Task:
        """Create a task and schedule its first run ``delay_s`` of
        virtual time from now."""
        task = _Task(self, name, fn)
        self._tasks.append(task)
        self.schedule(delay_s, task._resume)
        return task

    def schedule(self, delay_s: float, fn: Callable[[], None]) -> None:
        """Run ``fn`` on the scheduler thread at ``now + delay_s``."""
        self._seq += 1
        heapq.heappush(
            self._heap, (self.now + max(0.0, delay_s), self._seq, fn))

    def call_later(self, delay_s: float,
                   fn: Callable[[], None]) -> _VTimer:
        timer = _VTimer(fn)
        self.schedule(delay_s, timer._fire)
        return timer

    def current_task(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    # -- task-side blocking primitives ---------------------------------
    def sleep(self, seconds: float) -> None:
        """Park the calling task for ``seconds`` of virtual time."""
        task = self.current_task()
        if task is None:
            # A scheduler-thread callback (timer) tried to sleep: that
            # would deadlock the whole kernel.  Framework timer
            # callbacks are flag-writes by design; refuse loudly.
            raise RuntimeError(
                "virtual sleep outside a sim task (timer callbacks "
                "must not block)")
        self.schedule(seconds, task._resume)
        task._park(f"sleep({seconds:.6g}s)")

    def block(self, token: WaitToken, timeout_s: Optional[float],
              reason: str) -> bool:
        """Park the calling task on ``token`` until :meth:`notify`
        resolves it (True) or ``timeout_s`` virtual seconds pass
        (False).  ``token`` must be fresh for this wait."""
        task = self.current_task()
        if task is None:
            raise RuntimeError(f"block({reason}) outside a sim task")
        token.task = task
        if timeout_s is not None:
            def _timeout(token=token, task=task):
                if token.state == "waiting":
                    token.state = "timeout"
                    task._resume()

            # kept on the token so notify() can cancel it: a stale
            # timeout must neither fire nor advance virtual time (a
            # 600s timeout on a get that resolves in 1ms would
            # otherwise drag the final scenario clock to 600s)
            token.timer = self.call_later(timeout_s, _timeout)
        task._park(reason)
        return token.state == "notified"

    def notify(self, token: WaitToken, value: Any = None,
               delay_s: float = 0.0) -> bool:
        """Resolve a parked token (from any task or timer context);
        the parked task resumes ``delay_s`` virtual seconds from now.
        Returns False when the token already timed out / was notified."""
        if token.state != "waiting":
            return False
        token.state = "notified"
        token.value = value
        if token.timer is not None:
            token.timer.cancel()
            token.timer = None
        self.schedule(delay_s, token.task._resume)
        return True

    # -- scheduler ------------------------------------------------------
    def _start_thread(self, task: _Task) -> threading.Thread:
        prev = threading.stack_size(self._stack_bytes)
        try:
            thread = threading.Thread(
                target=task._run, name=f"sim:{task.name}", daemon=True)
            thread.start()
        finally:
            threading.stack_size(prev)
        return thread

    def run(self, max_virtual_s: Optional[float] = None) -> None:
        """Dispatch events until the heap drains.  Raises the first
        task error (protocol bug), :class:`DeadlockError` when parked
        tasks can never wake, or :class:`SimTimeBudgetExceeded` past
        ``max_virtual_s``.  Installs the virtual clock on the calling
        (scheduler) thread too, so timer callbacks reading the clock
        see virtual time."""
        if self._running:
            raise RuntimeError("SimKernel.run is not reentrant")
        self._running = True
        prev_clock = core_clock.installed()
        core_clock.install(self.clock)
        try:
            while self._heap:
                when, _seq, fn = heapq.heappop(self._heap)
                owner = getattr(fn, "__self__", None)
                if isinstance(owner, _VTimer) and owner._cancelled:
                    # cancelled timers are inert AND must not advance
                    # virtual time — the scenario clock would otherwise
                    # read "timeout horizon", not "work done"
                    continue
                if max_virtual_s is not None and when > max_virtual_s:
                    self._abort_parked()
                    raise SimTimeBudgetExceeded(
                        f"virtual time {when:.3f}s exceeds the "
                        f"{max_virtual_s:.3f}s budget "
                        f"({self._parked_summary()})")
                if when > self.now:
                    self.now = when
                fn()
                if self._failed:
                    self._abort_parked()
                    raise self._failed[0].error
            parked = [t for t in self._tasks if not t.done]
            if parked:
                summary = self._parked_summary()
                self._abort_parked()
                raise DeadlockError(
                    f"event heap drained with {len(parked)} task(s) "
                    f"still parked: {summary}")
        finally:
            self._running = False
            core_clock.install(prev_clock)

    def _parked_summary(self) -> str:
        parked = [t for t in self._tasks if not t.done]
        shown = ", ".join(
            f"{t.name}: {t.blocked_reason}" for t in parked[:8])
        more = f" (+{len(parked) - 8} more)" if len(parked) > 8 else ""
        return shown + more

    def _abort_parked(self) -> None:
        """Force-unwind every still-parked task with VirtualExit so no
        thread outlives the kernel (tests run many kernels)."""
        for task in self._tasks:
            if task.done or task.thread is None:
                continue
            task._abort = True
            task.go.set()
            # Bounded wait: a task parked in _park always unwinds, but
            # if one is wedged in a REAL blocking call (a scenario bug)
            # we leak the daemon thread instead of hanging teardown.
            if self._control.wait(timeout=10.0):
                self._control.clear()
        for task in self._tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)
