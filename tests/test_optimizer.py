"""DistributedOptimizer end-to-end: the minimum end-to-end slice of
SURVEY.md §7.1 step 3 — a model trained data-parallel over 8 devices in
one process, validating collectives + fusion + optimizer flow.

Parity target: horovod/torch/optimizer.py semantics (grad averaging,
backward_passes_per_step, compression, predivide) expressed as an optax
transform inside a jitted shard_map step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvt

AXIS = "world"


def mesh8():
    return Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,))


def make_mlp_params(key, din=8, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros((dh,)),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros((dout,)),
    }


def mlp_loss(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return jnp.mean((logits - y) ** 2)


def make_data(n=64, din=8, dout=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, din).astype(np.float32)
    w = rng.randn(din, dout).astype(np.float32)
    y = (x @ w).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def dp_train(tx, steps=20, **shard_kw):
    """Train with per-device batch shards; grads must be averaged by tx."""
    params = make_mlp_params(jax.random.PRNGKey(0))
    x, y = make_data()
    opt_state_holder = {}

    def step(params, opt_state, xs, ys):
        def body(p, s, xb, yb):
            loss, grads = jax.value_and_grad(mlp_loss)(p, xb, yb)
            updates, s = tx.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return p, s, jax.lax.pmean(loss, AXIS)

        return jax.jit(
            jax.shard_map(
                body,
                mesh=mesh8(),
                in_specs=(P(), P(), P(AXIS), P(AXIS)),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )
        )(params, opt_state, xs, ys)

    opt_state = tx.init(params)
    losses = []
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    return params, losses


class TestDistributedOptimizer:
    def test_loss_decreases(self, hvt):
        tx = hvt.DistributedOptimizer(optax.sgd(0.05), axis_name=AXIS)
        # 30 steps: jax.random init values differ across jax versions,
        # shifting the exact trajectory; plain local optax needs the
        # same step count for this ratio, so the bound stays a true
        # parity check rather than a version-calibrated constant.
        _, losses = dp_train(tx, steps=30)
        assert losses[-1] < losses[0] * 0.5

    def test_grads_match_full_batch_sgd(self, hvt):
        # DP-averaged gradient == full-batch gradient, so one step of
        # dp sgd must equal one step of local full-batch sgd.
        tx = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name=AXIS)
        params = make_mlp_params(jax.random.PRNGKey(0))
        x, y = make_data()

        dp_params, _ = dp_train(tx, steps=1)

        ref_tx = optax.sgd(0.1)
        g = jax.grad(mlp_loss)(params, x, y)
        upd, _ = ref_tx.update(g, ref_tx.init(params), params)
        ref_params = optax.apply_updates(params, upd)

        for k in params:
            np.testing.assert_allclose(
                np.asarray(dp_params[k]), np.asarray(ref_params[k]),
                rtol=1e-4, atol=1e-5,
            )

    def test_compression_still_converges(self, hvt):
        tx = hvt.DistributedOptimizer(
            optax.sgd(0.05), axis_name=AXIS,
            compression=hvt.Compression.bf16,
        )
        _, losses = dp_train(tx)
        assert losses[-1] < losses[0] * 0.6

    def test_backward_passes_per_step(self, hvt):
        tx = hvt.DistributedOptimizer(
            optax.sgd(0.05), axis_name=AXIS, backward_passes_per_step=2,
        )
        params = make_mlp_params(jax.random.PRNGKey(0))
        x, y = make_data()
        opt_state = tx.init(params)

        def step(params, opt_state, xs, ys):
            def body(p, s, xb, yb):
                grads = jax.grad(mlp_loss)(p, xb, yb)
                updates, s = tx.update(grads, s, p)
                return optax.apply_updates(p, updates), s

            return jax.jit(
                jax.shard_map(
                    body, mesh=mesh8(),
                    in_specs=(P(), P(), P(AXIS), P(AXIS)),
                    out_specs=(P(), P()),
                    check_vma=False,
                )
            )(params, opt_state, xs, ys)

        p1, opt_state = step(params, opt_state, x, y)
        # mid-cycle: params unchanged
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(params[k])
            )
        p2, opt_state = step(p1, opt_state, x, y)
        # boundary: params moved
        moved = any(
            not np.allclose(np.asarray(p2[k]), np.asarray(params[k]))
            for k in params
        )
        assert moved

    def test_adasum_op(self, hvt):
        tx = hvt.DistributedOptimizer(
            optax.sgd(0.05), axis_name=AXIS, op=hvt.Adasum,
        )
        _, losses = dp_train(tx)
        assert losses[-1] < losses[0]

    def test_predivide_factor_equivalence(self, hvt):
        # predivide redistributes the averaging divisor; result must
        # match plain averaging.
        tx_a = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name=AXIS)
        tx_b = hvt.DistributedOptimizer(
            optax.sgd(0.1), axis_name=AXIS, gradient_predivide_factor=4.0,
        )
        pa, _ = dp_train(tx_a, steps=3)
        pb, _ = dp_train(tx_b, steps=3)
        for k in pa:
            np.testing.assert_allclose(
                np.asarray(pa[k]), np.asarray(pb[k]), rtol=1e-4, atol=1e-5
            )

    def test_eager_path_single_process(self, hvt):
        # axis_name=None → eager process-level reduce (identity, P=1)
        tx = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name=None)
        params = make_mlp_params(jax.random.PRNGKey(1))
        x, y = make_data(seed=1)
        opt_state = tx.init(params)
        g = jax.grad(mlp_loss)(params, x, y)
        updates, opt_state = tx.update(g, opt_state, params)
        p2 = optax.apply_updates(params, updates)
        assert float(mlp_loss(p2, x, y)) < float(mlp_loss(params, x, y))


class TestShardedDistributedOptimizer:
    """ZeRO-1 sharded optimizer (reduce_scatter grads -> shard update
    -> all_gather): must train identically to the unsharded
    DistributedOptimizer while holding only 1/N of the state."""

    def _train(self, tx, steps=15):
        params = make_mlp_params(jax.random.PRNGKey(0))
        x, y = make_data()

        def body(p, xb, yb):
            s = tx.init(p)

            def one(i, carry):
                p, s = carry
                loss, g = jax.value_and_grad(mlp_loss)(p, xb, yb)
                u, s = tx.update(g, s, p)
                return (optax.apply_updates(p, u), s)

            p, s = jax.lax.fori_loop(0, steps, one, (p, s))
            return p

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh8(),
                in_specs=(P(), P(AXIS), P(AXIS)),
                out_specs=P(), check_vma=False,
            )
        )(params, x, y)

    def test_matches_unsharded(self, hvt):
        p_sharded = self._train(
            hvt.ShardedDistributedOptimizer(optax.adam(1e-2), axis_name=AXIS)
        )
        p_dense = self._train(
            hvt.DistributedOptimizer(optax.adam(1e-2), axis_name=AXIS)
        )
        for k in p_dense:
            np.testing.assert_allclose(
                np.asarray(p_sharded[k]), np.asarray(p_dense[k]),
                rtol=2e-5, atol=2e-6,
            )

    def test_state_is_sharded(self, hvt):
        tx = hvt.ShardedDistributedOptimizer(optax.adam(1e-2), axis_name=AXIS)
        params = make_mlp_params(jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(v.shape)) for v in params.values())

        def body(p):
            s = tx.init(p)
            biggest = max(
                (l.size for l in jax.tree_util.tree_leaves(s) if l.ndim),
                default=0,
            )
            return jnp.asarray(biggest)

        biggest = int(jax.jit(jax.shard_map(
            body, mesh=mesh8(), in_specs=(P(),), out_specs=P(),
            check_vma=False,
        ))(params))
        assert biggest == -(-n_params // 8)  # ceil(P/N), not P

    def test_sum_op_and_compression(self, hvt):
        from horovod_tpu.comm.compression import Compression

        tx = hvt.ShardedDistributedOptimizer(
            optax.sgd(1e-3), axis_name=AXIS, average=False,
            compression=Compression.bf16,
        )
        p = self._train(tx)
        assert all(np.isfinite(np.asarray(v)).all() for v in p.values())

    def test_int8_compression_rejected(self, hvt):
        from horovod_tpu.comm.compression import Compression

        with pytest.raises(ValueError, match="int8"):
            hvt.ShardedDistributedOptimizer(
                optax.sgd(1e-3), axis_name=AXIS,
                compression=Compression.int8,
            )
