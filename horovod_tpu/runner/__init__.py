"""Launcher / runner: ``hvtpurun`` CLI and the programmatic ``run()``.

Parity surface: ``horovod/runner/`` — ``horovodrun`` (launch.py),
``horovod.run()`` (``__init__.py``), host parsing, safe shell
execution, and the elastic driver (horovod_tpu.elastic.driver).
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

from .hosts import (  # noqa: F401
    HostSlots,
    SlotInfo,
    get_host_assignments,
    parse_host_spec,
)
from .launch import (  # noqa: F401
    build_worker_env,
    find_free_port,
    launch_workers,
    main,
    parse_args,
)


class RunError(RuntimeError):
    """A worker failed during ``run()``; carries the rank's traceback."""

    def __init__(self, rank: int, worker_traceback: str):
        super().__init__(
            f"rank {rank} failed:\n{worker_traceback}"
        )
        self.rank = rank
        self.worker_traceback = worker_traceback


def _dump_fn(fn: Callable, args, kwargs, path: str, key: str):
    """Pickle + HMAC-sign the function blob (parity: secret.py-signed
    service messages; workers refuse unsigned/tampered payloads)."""
    from . import secret

    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover - cloudpickle is available
        import pickle as pickler
    blob = pickler.dumps((fn, tuple(args), dict(kwargs or {})))
    with open(path, "wb") as f:
        f.write(secret.sign(key, blob))


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    np: int = 2,
    cpu_devices: Optional[int] = None,
    hosts: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = 600.0,
    start_timeout: Optional[float] = None,  # rendezvous window (env)
    extra_flags: Optional[List[str]] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` local worker processes and
    return the per-rank results, ordered by rank.

    Parity: ``horovod.run()`` (horovod/runner/__init__.py) — the
    function rides cloudpickle to each rank; each rank's return value is
    collected by the launcher.  ``cpu_devices`` forces the CPU platform
    with that many XLA devices per worker (the localhost-as-cluster test
    mode; SURVEY.md §4 pattern 2).  ``timeout`` is a hard deadline for
    the whole job (None = unlimited) — unlike ``hvtpurun``, the
    programmatic API defaults to bounded so test harnesses can't hang.
    ``start_timeout`` only bounds the workers' rendezvous window
    (parity: horovod.run's start_timeout), not job duration.
    """
    from . import launch as launch_mod
    from . import secret

    job_key = secret.make_secret_key()
    with tempfile.TemporaryDirectory(prefix="hvtpurun_") as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        out_dir = os.path.join(tmp, "results")
        os.makedirs(out_dir)
        _dump_fn(fn, args, kwargs, fn_path, job_key)
        argv = ["-np", str(np)]
        if cpu_devices is not None:
            argv += ["--cpu-devices", str(cpu_devices)]
        if verbose:
            argv += ["--verbose"]
        if start_timeout is not None:
            argv += ["--start-timeout", str(start_timeout)]
        argv += extra_flags or []
        argv += [
            sys.executable, "-m", "horovod_tpu.runner.run_task",
            fn_path, out_dir,
        ]
        ns = launch_mod.parse_args(argv)
        base_env = dict(os.environ)
        base_env.update(env or {})
        # key travels by 0600 file, not env value: the ssh path
        # serializes the worker env into world-readable argv (the
        # fn/result channel already requires a shared filesystem, so
        # the key file rides the same one)
        key_path = os.path.join(tmp, "job.key")
        secret.write_key_file(job_key, key_path)
        base_env[secret.ENV_KEY_FILE] = key_path
        base_env.pop(secret.ENV_KEY, None)
        # hosts: e.g. "localhost:2,127.0.0.1:2" to shape local/cross
        # topology while still spawning locally (both names are local)
        host_spec = hosts or f"localhost:{np}"
        slots = get_host_assignments(parse_host_spec(host_spec), np)
        port = launch_mod.find_free_port()
        code = launch_workers(
            ns.command,
            slots,
            "127.0.0.1",
            port,
            args=ns,
            base_env=base_env,
            job_timeout=timeout,
        )
        # Collect every rank's payload FIRST, then report the most
        # informative failure: a rank that wrote (ok=False, traceback)
        # beats 'no result file' from a peer the launcher terminated.
        payloads: Dict[int, tuple] = {}
        bad_signature: Dict[int, str] = {}
        for r in range(np):
            path = os.path.join(out_dir, f"rank_{r}.pkl")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    # verify the worker's signature before unpickling —
                    # result files cross the same trust boundary as the
                    # shipped function.  A bad signature on one rank must
                    # not abort collection of the rest: record it and keep
                    # going so the report carries every rank's status
                    # (the tampered blob is still never unpickled).
                    try:
                        blob = secret.verify(job_key, f.read())
                    except secret.SignatureError as e:
                        bad_signature[r] = str(e)
                        continue
                payloads[r] = pickle.loads(blob)
        def _others(r: int) -> str:
            return "Other ranks: " + ", ".join(
                f"rank {q}: "
                + ("failed" if q in payloads and not payloads[q][0] else
                   "ok" if q in payloads else
                   "bad signature" if q in bad_signature else
                   "no result file")
                for q in range(np) if q != r
            )

        for r in range(np):
            item = payloads.get(r)
            if item is not None and not item[0]:
                # a concurrent tampering signal must not be buried under
                # an ordinary worker crash — carry every rank's status
                raise RunError(r, item[1] + "\n" + _others(r))
        if bad_signature:
            r = min(bad_signature)
            raise RunError(
                r,
                f"result file failed signature verification "
                f"({bad_signature[r]}); the blob was not unpickled. "
                + _others(r),
            )
        for r in range(np):
            if r not in payloads:
                raise RunError(
                    r,
                    f"no result file (worker exit code {code}; it may "
                    "have crashed or been terminated before writing "
                    "results)",
                )
        if code != 0:
            raise RunError(-1, f"launcher observed exit code {code}")
        return [payloads[r][1] for r in range(np)]


def run_elastic(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    num_proc: int = 2,
    min_np: Optional[int] = None,
    max_np: Optional[int] = None,
    cpu_devices: Optional[int] = 1,
    host_discovery_script: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    start_timeout: Optional[float] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run ``fn`` under the ELASTIC driver and return per-rank results
    of the final world, ordered by rank.

    Parity: ``horovod.spark.run_elastic`` (horovod/spark/__init__.py)
    / the elastic half of ``horovodrun`` — ``fn`` is expected to follow
    the elastic contract (build a ``hvd.elastic.State``, decorate the
    loop with ``@hvd.elastic.run``); membership changes restart it from
    the last commit.  Without ``host_discovery_script`` a static
    ``localhost:num_proc`` discovery is generated (the reference's
    local-mode CI shape); with one, the world resizes live as its
    output changes.
    """
    from . import launch as launch_mod
    from . import secret
    from ..elastic.driver import run_elastic_driver

    job_key = secret.make_secret_key()
    with tempfile.TemporaryDirectory(prefix="hvtpurun_el_") as tmp:
        fn_path = os.path.join(tmp, "fn.pkl")
        out_dir = os.path.join(tmp, "results")
        os.makedirs(out_dir)
        _dump_fn(fn, args, kwargs, fn_path, job_key)
        if host_discovery_script is None:
            host_discovery_script = os.path.join(tmp, "discover.sh")
            with open(host_discovery_script, "w") as f:
                f.write(f"#!/bin/sh\necho localhost:{num_proc}\n")
            os.chmod(host_discovery_script, 0o755)
        argv = ["--host-discovery-script", host_discovery_script,
                "-np", str(num_proc)]
        if min_np is not None:
            argv += ["--min-np", str(min_np)]
        if max_np is not None:
            argv += ["--max-np", str(max_np)]
        if cpu_devices is not None:
            argv += ["--cpu-devices", str(cpu_devices)]
        if start_timeout is not None:
            argv += ["--start-timeout", str(start_timeout)]
        if verbose:
            argv += ["--verbose"]
        argv += ["--", sys.executable, "-m",
                 "horovod_tpu.runner.run_task", fn_path, out_dir]
        ns = launch_mod.parse_args(argv)
        key_path = os.path.join(tmp, "job.key")
        secret.write_key_file(job_key, key_path)
        # the elastic driver builds worker env from the launcher's
        # process env; scope the additions to this call
        added = {secret.ENV_KEY_FILE: key_path, **(env or {})}
        # the key must travel by file, never env value (the ssh path
        # serializes env into argv) — and the caller's own value must
        # come back afterwards, so it joins the save/restore set
        saved = {k: os.environ.get(k)
                 for k in (*added, secret.ENV_KEY)}
        os.environ.update(added)
        os.environ.pop(secret.ENV_KEY, None)
        try:
            code, driver = run_elastic_driver(ns)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if code != 0:
            raise RunError(-1, f"elastic driver exit code {code}")
        # collect the FINAL world's results only: a shrink leaves
        # higher-rank files from earlier incarnations behind, and a
        # recovered crash leaves an ok=False file — both stale
        final_np = driver.final_world_size or 0
        results: Dict[int, Any] = {}
        for name in sorted(os.listdir(out_dir)):
            if not (name.startswith("rank_") and name.endswith(".pkl")):
                continue
            r = int(name[len("rank_"):-len(".pkl")])
            if r >= final_np:
                continue
            try:
                with open(os.path.join(out_dir, name), "rb") as f:
                    blob = secret.verify(job_key, f.read())
            except secret.SignatureError as e:
                raise RunError(
                    r, f"result file failed signature verification "
                       f"({e}); the blob was not unpickled.")
            ok, payload = pickle.loads(blob)
            if not ok:
                raise RunError(r, payload)
            results[r] = payload
        missing = [r for r in range(final_np) if r not in results]
        if missing:
            raise RunError(
                missing[0],
                f"no result file for rank(s) {missing} of the final "
                f"{final_np}-rank world")
        return [results[r] for r in sorted(results)]
