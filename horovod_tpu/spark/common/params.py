"""Estimator parameter machinery.

Parity surface: ``horovod/spark/common/params.py`` (``EstimatorParams``)
— the reference builds on ``pyspark.ml.param.Params``: every knob is a
named Param with a ``setFoo``/``getFoo`` pair and a default, validated
at fit time.  pyspark is optional here, so this is a dependency-free
re-implementation of the same contract: snake_case constructor kwargs,
camelCase setter/getter pairs generated from the param table, unknown
names rejected eagerly (a typo'd param must not silently train with a
default).
"""

from __future__ import annotations

from typing import Any, Dict


def _camel(name: str) -> str:
    return "".join(p.capitalize() for p in name.split("_"))


class Params:
    """Minimal pyspark-ml-style Params: subclasses declare
    ``_param_defs = {snake_name: default}``; instances get
    ``set<Camel>(v)`` (chainable) and ``get<Camel>()`` for each."""

    _param_defs: Dict[str, Any] = {}

    def __init__(self, **kwargs):
        import copy

        # merge param tables down the MRO so Torch/Keras subclasses
        # inherit the shared EstimatorParams names; deep-copied so a
        # mutable default ([], {}) appended to on one instance cannot
        # leak into the class table and every later instance
        defs: Dict[str, Any] = {}
        for klass in reversed(type(self).__mro__):
            defs.update(getattr(klass, "_param_defs", {}))
        self._params = copy.deepcopy(defs)
        unknown = set(kwargs) - set(defs)
        if unknown:
            raise ValueError(
                f"unknown param(s) {sorted(unknown)} for "
                f"{type(self).__name__}; valid: {sorted(defs)}"
            )
        self._params.update(kwargs)

    def __getattr__(self, name: str):
        # generated accessors: setEpochs(5) / getEpochs()
        params = self.__dict__.get("_params")
        if params is not None:
            for snake in params:
                cam = _camel(snake)
                if name == f"get{cam}":
                    return lambda snake=snake: params[snake]
                if name == f"set{cam}":
                    def _set(value, snake=snake):
                        params[snake] = value
                        return self
                    return _set
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def _get(self, name: str):
        return self._params[name]

    def param_dict(self) -> Dict[str, Any]:
        return dict(self._params)


class EstimatorParams(Params):
    """The shared estimator knob set (reference: EstimatorParams).

    Names and defaults follow ``horovod/spark/common/params.py``;
    knobs whose reference meaning is Petastorm-specific
    (``train_reader_num_workers`` et al.) are accepted for source
    compat and ignored by the npz data path.
    """

    _param_defs = {
        "num_proc": None,           # ranks (default: backend's)
        "model": None,
        "backend": None,            # common.backend.Backend
        "store": None,              # common.store.Store
        "loss": None,
        "metrics": [],
        "feature_cols": None,       # list[str]
        "label_cols": None,         # list[str]
        "output_cols": None,        # transform() output column names
        "validation": None,         # float fraction | indicator column
        "sample_weight_col": None,
        "compression": None,
        # reference spelling of the same knob (horovod estimators name
        # it gradient_compression); either works, reference wins when
        # both are set
        "gradient_compression": None,
        # per-output loss scaling for multi-output models (reference:
        # loss_weights on both estimators)
        "loss_weights": None,
        "batch_size": 32,
        "val_batch_size": None,
        "epochs": 1,
        "verbose": 1,
        "shuffle": True,
        "shuffle_buffer_size": None,   # accepted; npz path shuffles fully
        "callbacks": [],
        "random_seed": None,
        "run_id": None,
        # load the run's latest Store checkpoint before training (rank
        # 0 loads, broadcast propagates) — the reference's resume
        # semantics; default is a fresh fit from the shipped weights
        "resume_from_checkpoint": False,
        "train_steps_per_epoch": None,
        "validation_steps_per_epoch": None,
        # (features, labels) hook applied to each rank's shard at data
        # load — one contract across the torch and keras trainers
        "transformation_fn": None,
        "partitions_per_process": None,   # petastorm-era; ignored
        "train_reader_num_workers": None, # petastorm-era; ignored
        "val_reader_num_workers": None,   # petastorm-era; ignored
        "inmemory_cache_all": True,       # npz path is always in-memory
        "label_shapes": None,
    }
