"""Elastic keras state (parity: ``horovod/tensorflow/keras/elastic.py``
``KerasState``): the tf.keras alias of ``TensorFlowKerasState`` plus
the shared ``run`` decorator."""

from ...elastic import run  # noqa: F401  (parity: hvd.elastic.run)
from ...keras.elastic import (  # noqa: F401
    CommitStateCallback,
    UpdateBatchStateCallback,
    UpdateEpochStateCallback,
)
from ..elastic import TensorFlowKerasState

# Reference class name for the tf.keras path: KerasState(model,
# optimizer=None, **kwargs) with commit/restore/sync semantics.
KerasState = TensorFlowKerasState
