// Eager mini-controller: readiness coordination, response cache, fusion
// planning, group gating, join, stall inspection.
//
// Parity map (reference -> here):
//   horovod/common/tensor_queue.cc  TensorQueue            -> TensorQueue
//   horovod/common/controller.cc    Controller::ComputeResponseList,
//                                   MessageTable            -> Controller
//   horovod/common/controller.cc    Controller::FuseResponses -> FuseResponses
//   horovod/common/response_cache.cc ResponseCache          -> ResponseCache
//   horovod/common/group_table.cc   GroupTable              -> GroupTable
//   horovod/common/stall_inspector.cc StallInspector        -> StallInspector
//
// Design departure (SURVEY.md §7.0): the reference's controller runs on a
// background thread inside each rank and talks MPI/Gloo.  Here the
// controller is a passive state machine driven by the Python cycle loop
// (horovod_tpu/eager/controller.py); the transport between ranks is the
// JAX coordination-service KV store, and the data plane is XLA
// collectives.  Everything order-sensitive (cache mutation, fusion
// order) happens in response-apply order, which is identical on every
// rank — that is what keeps rank-local state consistent without any
// extra coordination traffic.
#pragma once

#include <atomic>
#include <deque>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <unordered_map>

#include "message.h"

namespace hvt {

double NowSeconds();  // monotonic

// --------------------------------------------------------------------------
// TensorQueue (parity: tensor_queue.cc)
// --------------------------------------------------------------------------
class TensorQueue {
 public:
  // Returns false if a pending entry with the same name already exists
  // (parity: AddToTensorQueue's DUPLICATE_NAME_ERROR).
  bool Add(Entry e);
  // Pop up to the full pending list for this cycle (parity:
  // PopMessagesFromQueue); entries move to in-flight keyed by name.
  // limit > 0 caps the drain at that many entries (atomic-burst cap:
  // one wire unit == one application burst even when the next burst
  // already started queueing).
  std::vector<Entry> Drain(size_t limit = 0);
  // Remove finished entries by name; returns their seq ids (parity:
  // GetTensorEntriesFromResponse + PopMessagesFromQueue bookkeeping).
  std::vector<uint64_t> Finish(const std::vector<std::string>& names);
  // Copies of the entries currently in flight (drained but not yet
  // answered) — re-announced on a coordinator-requested cache resync.
  std::vector<Entry> InFlightSnapshot() const;
  int64_t pending_count() const;
  int64_t pending_bytes() const;

 private:
  mutable std::mutex mu_;
  std::deque<Entry> pending_;
  std::unordered_map<std::string, Entry> in_flight_;
  std::set<std::string> pending_names_;
};

// --------------------------------------------------------------------------
// ResponseCache (parity: response_cache.cc)
// --------------------------------------------------------------------------
// Caches the full signature of repeated requests so steady-state cycles
// exchange small bit ids instead of serialized requests.  All mutation
// happens in response-apply order => identical on all ranks.
class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  static std::string Signature(const Entry& e);
  // -1 if absent, else bit id. Does NOT touch LRU order (enqueue-side
  // lookups happen in rank-local order; only Apply-side touches are
  // replicated).
  int64_t Lookup(const std::string& signature) const;
  // Insert-or-touch in apply order; evicts LRU when over capacity.
  // Returns the bit id.
  uint32_t Put(const std::string& signature, const Entry& e);
  bool GetEntryForBit(uint32_t bit, Entry* out) const;
  size_t size() const { return lru_.size(); }
  size_t capacity() const { return capacity_; }

 private:
  struct CacheItem {
    std::string signature;
    Entry entry;
    uint32_t bit;
  };
  size_t capacity_;
  std::list<CacheItem> lru_;  // front = most recent
  std::unordered_map<std::string, std::list<CacheItem>::iterator> by_sig_;
  std::unordered_map<uint32_t, std::list<CacheItem>::iterator> by_bit_;
  std::set<uint32_t> free_bits_;
  uint32_t next_bit_ = 0;
};

// --------------------------------------------------------------------------
// GroupTable (parity: group_table.cc)
// --------------------------------------------------------------------------
class GroupTable {
 public:
  void DeclareGroup(int64_t group_id, int32_t size) { sizes_[group_id] = size; }
  int32_t GroupSize(int64_t group_id) const {
    auto it = sizes_.find(group_id);
    return it == sizes_.end() ? -1 : it->second;
  }

 private:
  std::unordered_map<int64_t, int32_t> sizes_;
};

// --------------------------------------------------------------------------
// StallInspector (parity: stall_inspector.cc)
// --------------------------------------------------------------------------
struct StallEntry {
  std::string name;
  double waiting_s = 0;
  std::vector<int32_t> present_ranks;
  std::vector<int32_t> missing_ranks;
};

// --------------------------------------------------------------------------
// Controller
// --------------------------------------------------------------------------
class Controller {
 public:
  Controller(int32_t rank, int32_t size, int64_t fusion_threshold_bytes,
             size_t cache_capacity, double stall_warn_s, double stall_abort_s);

  // ---- rank-local side ----
  uint64_t Enqueue(Entry e, Status* status);
  void DeclareGroup(int64_t group_id, int32_t size) {
    group_table_.DeclareGroup(group_id, size);
  }
  void RegisterProcessSet(int32_t psid, std::vector<int32_t> ranks);
  void SetJoined() { joined_ = true; }
  // Announce this rank wants to shut down (emitted in every
  // subsequent DrainRequests).  The rank keeps cycling — serving
  // coordination — until the coordinator sees EVERY rank's
  // announcement and broadcasts ResponseList.shutdown (global
  // quiesce); meanwhile pending collectives that NEED an announced
  // rank fail promptly with an error response instead of stalling
  // (parity: horovod_shutdown's negotiated DONE + the "Horovod has
  // been shut down" error for stragglers).
  void SetShutdown() { shutdown_ = true; }
  // Coordinator-side: publish autotuned params in every ResponseList
  // so all ranks apply identical values (parity: ParameterManager
  // broadcasting tuned params from the coordinator).
  void SetTuned(int64_t fusion_threshold, int32_t cycle_time_us) {
    std::lock_guard<std::mutex> g(mu_);
    tuned_threshold_ = fusion_threshold;
    tuned_cycle_us_ = cycle_time_us;
  }
  // Steady-state bypass cadence: every Nth all-cache-hit cycle sends a
  // full-resync request blob instead of the compact bit vector (0
  // disables bypass entirely).  Cycle-thread + init-time only.
  void SetResyncEvery(int64_t n) { resync_every_ = n; }
  // Rank-side re-anchor (mispredict recovery / quiesce rollback): the
  // next DrainRequests emits a full-entry resync frame — re-announcing
  // in-flight ops — exactly as if the coordinator had requested
  // cache_resync_needed.
  void ForceResync() {
    resync_flush_ = true;
    bypass_streak_ = 0;
  }
  // Serialize this cycle's RequestList (drains the queue into
  // in-flight); limit > 0 caps the drained entries (atomic-burst cap).
  std::vector<uint8_t> DrainRequests(int64_t limit = 0);
  // Apply an agreed ResponseList: update cache + queue; out_finished gets
  // the seq ids completed by this response list, in response order.
  ResponseList ApplyResponses(const uint8_t* data, size_t len,
                              std::vector<uint64_t>* out_finished);

  // Steady-state schedule prediction: the ResponseList the
  // coordinator will emit for a pure bypass cycle of exactly `bits`
  // (deterministic in the replicated cache + fusion threshold).
  // Empty vector when a bit is unknown.
  std::vector<uint8_t> PredictResponses(const std::vector<uint32_t>& bits);
  // Eagerly retire predicted-executed in-flight entries by name.
  std::vector<uint64_t> FinishNames(const std::vector<std::string>& names);

  // ---- coordinator side (rank 0; parity: MessageTable at rank 0) ----
  void Ingest(const uint8_t* data, size_t len);
  // Decide globally-ready set, fuse, clear consumed coordination state.
  // (parity: Controller::ComputeResponseList + FuseResponses)
  std::vector<uint8_t> ComputeResponses();

  std::vector<StallEntry> CheckStalls() const;

  int64_t pending_count() const { return queue_.pending_count(); }
  int64_t pending_bytes() const { return queue_.pending_bytes(); }
  size_t cache_size() const { return cache_.size(); }
  int32_t rank() const { return rank_; }
  int32_t size() const { return size_; }
  void set_fusion_threshold(int64_t b) { fusion_threshold_ = b; }
  int64_t fusion_threshold() const { return fusion_threshold_; }

 private:
  // (rank, burst_id) reference into units_: the atomic burst unit this
  // coordination belongs to on that rank's stream.
  using UnitRef = std::pair<int32_t, uint32_t>;

  struct PendingCoordination {
    Entry entry;                 // from the first rank that reported it
    std::set<int32_t> ranks;     // ranks that reported ready
    double first_seen_s = 0;
    int32_t first_rank = -1;     // who contributed `entry`
    // ranks whose submission disagreed with `entry` on the agreement
    // surface (SameParams), with what they submitted — turned into a
    // named-rank error response instead of a silent mis-fuse/stall.
    std::map<int32_t, Entry> mismatched;
    // burst units referencing this occurrence; release is gated on
    // every one being completely ready (see BuildResponseList).
    std::set<UnitRef> units;
    // ranks whose announcement carried the PREDICTED confirmation flag
    std::set<int32_t> predicted;
    // creation index — deterministic component emission order
    uint64_t seq = 0;
  };

  static std::string TableKey(const Entry& e);
  // Cross-rank agreement surface; group_id and allgather/alltoall
  // dim 0 deliberately excluded (rank-local bookkeeping / legitimate
  // per-rank raggedness).  Must match fallback._same_params.
  static bool SameParams(const Entry& a, const Entry& b);
  // Submission summary for mismatch diagnostics; byte-identical to
  // fallback._entry_desc.
  static std::string EntryDesc(const Entry& e);
  // Record one rank's announcement, tracking per-rank conflicts.
  // occurrence=true (burst-unit announcements) opens a NEW occurrence
  // relative to ones this rank already announced; occurrence=false
  // matches idempotently (legacy / resync re-announcements).  Must
  // match fallback._table_add.
  PendingCoordination* TableAdd(Entry e, int32_t rank, double now,
                                bool occurrence, std::string* out_key);
  // Pop a released coordination off its occurrence queue and drop its
  // key from every burst unit that referenced it.
  void ReleaseFront(const std::string& key, const PendingCoordination& pc);
  int32_t RequiredRanks(int32_t psid) const;
  std::vector<int32_t> ProcessSetRanks(int32_t psid) const;
  int32_t PresentCount(const PendingCoordination& pc) const;
  ResponseList BuildResponseList();
  void FuseResponses(std::vector<Response>* responses) const;

  int32_t rank_, size_;
  int64_t fusion_threshold_;
  double stall_warn_s_, stall_abort_s_;

  TensorQueue queue_;
  ResponseCache cache_;
  GroupTable group_table_;
  // set by the frontend thread, read lock-free by the cycle thread's
  // DrainRequests — atomics, not a data race
  std::atomic<bool> joined_{false};
  std::atomic<bool> shutdown_{false};

  // cycle-thread-only bypass bookkeeping (drain/apply both run on the
  // Python cycle loop's thread)
  int64_t resync_every_ = 64;
  int64_t bypass_streak_ = 0;
  bool resync_flush_ = false;
  // per-rank monotonic burst-unit counter (drain side)
  uint32_t burst_seq_ = 0;

  // coordinator state.  Each key holds an OCCURRENCE QUEUE of pending
  // coordinations (front = oldest): with prediction on, a rank's
  // fire-and-forget confirmations can announce the same tensor names
  // for several bursts before the coordinator catches up.
  bool resync_needed_ = false;
  int64_t tuned_threshold_ = -1;
  int32_t tuned_cycle_us_ = -1;
  std::map<std::string, std::deque<PendingCoordination>>
      message_table_;  // by (psid, name), ordered for determinism
  // (rank, burst_id) -> table keys forming that rank's atomic unit
  std::map<UnitRef, std::set<std::string>> units_;
  uint64_t pc_seq_ = 0;
  std::set<int32_t> joined_ranks_;
  int32_t last_joined_rank_ = -1;
  std::set<int32_t> shutdown_ranks_;
  std::unordered_map<int32_t, std::vector<int32_t>> process_sets_;
  mutable std::mutex mu_;
};

}  // namespace hvt
