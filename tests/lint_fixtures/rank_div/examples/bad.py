"""rank-divergence fixture: collectives under rank-dependent branches.

Every pattern here is a known-bad case the pass must flag.
"""

import horovod_tpu as hvt


def direct_rank_test(grads):
    # Bad: broadcast only on rank 0 — other ranks never enter the op.
    if hvt.rank() == 0:
        hvt.broadcast(grads, root_rank=0)


def tainted_local(grads):
    # Bad: the rank value flows through a local before the test.
    r = hvt.rank()
    if r > 0:
        grads = hvt.allreduce(grads)
    return grads


def else_arm(state, grads):
    # Bad: the else arm runs on the complement set of ranks.
    if state.rank == 0:
        pass
    else:
        hvt.barrier()


def ternary(loss):
    # Bad: rank-conditional collective inside a conditional expression.
    return hvt.allreduce(loss) if hvt.local_rank() == 0 else loss
