"""State-distribution helpers.

Parity surface: ``horovod/torch/functions.py`` —
``broadcast_parameters``, ``broadcast_optimizer_state``,
``broadcast_object`` — plus ``allgather_object``, the utilities every
Horovod training script calls once at startup to fan rank 0's state out
to the world (SURVEY.md §5.4).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import eager
from ..core import state as core_state


def _write_back(container, new):
    """Update mutable containers (dict/list) in place with the new
    leaves so the reference's statement-style call pattern
    (``hvd.broadcast_parameters(state_dict)``) works — a user migrating
    from the in-place torch API would otherwise silently keep the old
    values.  Tuples are immutable but their MUTABLE descendants are
    still updated in place (a dict held from inside a tuple must not
    go stale); the functional return value is always complete.
    Structure mismatches raise (tree_map guarantees matching trees, so
    a mismatch is a bug, not something to skip silently)."""
    if isinstance(container, dict) and isinstance(new, dict):
        for k in container:
            child = _write_back(container[k], new[k])
            if child is not None:
                container[k] = child
        return None
    if isinstance(container, list) and isinstance(new, list):
        for i in range(len(container)):
            child = _write_back(container[i], new[i])
            if child is not None:
                container[i] = child
        return None
    if isinstance(container, tuple) and isinstance(new, tuple):
        for c, n in zip(container, new):
            _write_back(c, n)
        return new  # the tuple slot itself is replaced by the parent
    return new  # leaf (or other immutable node): caller assigns


def broadcast_parameters(params, root_rank: int = 0, process_set=None):
    """Broadcast a pytree of arrays from ``root_rank`` to all ranks.

    All leaves ride ONE fused byte buffer (packed in parallel by the
    native thread pool — the same FusionBufferManager-style fast path
    as the torch frontend's broadcast_parameters): one collective and
    one compiled program for the whole startup fan-out instead of one
    per leaf, which also lets the pod-shape multi-lane transport
    engage (per-leaf payloads rarely clear its size threshold).

    Returns the broadcast tree; when ``params`` is built of mutable
    containers (dicts/lists), their leaves are ALSO updated in place so
    the reference's statement-style idiom works unchanged.  (JAX
    arrays themselves are immutable — in-place here means container
    slots, not buffers.)
    """
    core_state.require_init("broadcast_parameters")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if len(leaves) <= 1:
        new = jax.tree_util.tree_map(
            lambda t: eager.broadcast(
                jnp.asarray(t), root_rank=root_rank,
                process_set=process_set
            ),
            params,
        )
        _write_back(params, new)
        return new

    from ..comm.packing import pack_bytes, unpack_bytes

    raws = [np.asarray(jnp.asarray(l)) for l in leaves]
    buf, specs = pack_bytes(raws)
    out = np.asarray(eager.broadcast(
        jnp.asarray(buf), root_rank=root_rank, process_set=process_set
    ))
    pieces = [jnp.asarray(p) for p in unpack_bytes(out, specs)]
    new = jax.tree_util.tree_unflatten(treedef, pieces)
    _write_back(params, new)
    return new


def broadcast_optimizer_state(opt_state, root_rank: int = 0, process_set=None):
    """Broadcast optimizer state (any pytree; non-array leaves go via
    ``broadcast_object``)."""
    core_state.require_init("broadcast_optimizer_state")

    def bcast_leaf(t):
        if isinstance(t, (jax.Array, np.ndarray)) or jnp.isscalar(t):
            return eager.broadcast(
                jnp.asarray(t), root_rank=root_rank, process_set=process_set
            )
        return broadcast_object(t, root_rank=root_rank, process_set=process_set)

    new = jax.tree_util.tree_map(bcast_leaf, opt_state)
    # Reference parity: scalar state entries (step counters, lr floats)
    # come back as Python scalars, not 0-d arrays — the torch version
    # casts back after the wire trip, and the in-place write-back must
    # not clobber the caller's dict with un-serializable Arrays.
    new = jax.tree_util.tree_map(
        lambda orig, n: (type(orig)(n.item())
                         if isinstance(orig, (bool, int, float))
                         and hasattr(n, "item") else n),
        opt_state, new,
    )
    # same statement-style ergonomics as broadcast_parameters
    _write_back(opt_state, new)
    return new


def broadcast_object(obj: Any, root_rank: int = 0, process_set=None) -> Any:
    """Pickle on root, broadcast size then payload, unpickle everywhere.

    Parity: ``horovod/torch/functions.py broadcast_object`` (same
    two-phase size/payload wire protocol).
    """
    core_state.require_init("broadcast_object")
    st = core_state.global_state()
    if st.size == 1:
        return obj

    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)

    # uint32 size header: stays exact without jax_enable_x64 (bounds
    # one pickled object at 4 GiB, same as the reference's int wire).
    size = eager.broadcast(
        jnp.asarray(payload.size, jnp.uint32),
        root_rank=root_rank,
        process_set=process_set,
    )
    n = int(size)
    local = payload if st.rank == root_rank else np.zeros((n,), np.uint8)
    wire = eager.broadcast(
        jnp.asarray(local[:n]), root_rank=root_rank, process_set=process_set
    )
    return pickle.loads(np.asarray(wire).tobytes())


def allgather_object(obj: Any, process_set=None):
    """Gather a picklable object from every rank; returns a list ordered
    by rank (parity: hvd.allgather_object)."""
    core_state.require_init("allgather_object")
    st = core_state.global_state()
    if st.size == 1:
        return [obj]

    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    gathered_sizes = np.asarray(
        eager.allgather(
            jnp.asarray([payload.size], jnp.uint32), process_set=process_set
        )
    )
    blob = np.asarray(
        eager.allgather(jnp.asarray(payload), process_set=process_set)
    ).tobytes()
    out, off = [], 0
    for s in gathered_sizes:
        out.append(pickle.loads(blob[off : off + int(s)]))
        off += int(s)
    return out
