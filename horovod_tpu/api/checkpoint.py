"""Checkpoint / resume helpers.

Parity surface (SURVEY.md §5.4): the reference has no general
checkpoint subsystem — its idioms are (a) elastic State commit/restore,
(b) ``broadcast_parameters``/``broadcast_object`` fanning out a rank-0
restored checkpoint, (c) rank-0-writes-checkpoint as an example-level
convention.  The TPU-native replacement the survey prescribes is
orbax-style async checkpointing; this module provides it with the same
rank-0 conventions, falling back to pickle when orbax is unavailable.

API::

    ckpt = hvt.Checkpointer(dir)         # rank-0 writes, async
    ckpt.save(step, {"params": params, "opt_state": opt_state})
    state = ckpt.restore()                # newest step (rank 0 reads)
    state = hvt.broadcast_object(state)   # classic reference fanout
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import pickle
import re
import sys
import threading
import weakref
from typing import Any, Dict, List, Optional

from ..core import state as core_state


def _is_coordinator() -> bool:
    # require_init: before init() every process would default to rank 0
    # and N ranks would race writes into the same checkpoint dir
    return core_state.require_init("checkpointing").rank == 0


# One module-level exit hook over a weak set: per-instance
# atexit.register would pin every Checkpointer (a per-step
# save_checkpoint loop creates many) for process lifetime.
_live_checkpointers: "weakref.WeakSet[Checkpointer]" = weakref.WeakSet()


@atexit.register
def _flush_pending_saves_at_exit():
    for ckpt in list(_live_checkpointers):
        try:
            ckpt.wait()
        except Exception as e:  # can't raise during interpreter exit
            print(f"hvtpu.Checkpointer: {e}", file=sys.stderr)


def step_dir_name(step: int) -> str:
    """Shared step-directory naming (used by both checkpointers — the
    layouts must never diverge)."""
    return f"step_{step:012d}"


def list_steps(directory: str, require_file: Optional[str] = None
               ) -> List[int]:
    """Sorted step numbers under ``directory``; ``require_file`` keeps
    only steps whose dir contains that file (commit marker)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"step_(\d+)", name)
        if not m:
            continue
        if require_file and not os.path.exists(
                os.path.join(directory, name, require_file)):
            continue
        out.append(int(m.group(1)))
    return sorted(out)


class Checkpointer:
    """Async, rank-0-writes checkpointing (orbax-backed when available).

    ``save`` returns immediately — serialization happens on a worker
    thread (the orbax async idiom); ``wait`` blocks until the last save
    is durable.  ``restore`` loads the newest (or a given) step.
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None,
                 use_orbax: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                use_orbax = True
            except ImportError:  # pragma: no cover - orbax is baked in
                use_orbax = False
        self.use_orbax = use_orbax
        self._pending: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # a daemon writer thread would be killed at interpreter exit,
        # silently losing the final checkpoint of a run that never
        # called wait() — the module exit hook joins pending saves
        _live_checkpointers.add(self)
        if _is_coordinator():
            os.makedirs(self.directory, exist_ok=True)
        if self.use_orbax:
            import orbax.checkpoint as ocp

            self._ocp = ocp.StandardCheckpointer()

    # -- write side ----------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, step_dir_name(step))

    def save(self, step: int, payload: Dict[str, Any]):
        """Queue an async save of ``payload`` at ``step`` (rank 0 only;
        other ranks no-op, like the reference's rank-0 convention)."""
        if not _is_coordinator():
            return
        self.wait()  # one in flight at a time (orbax semantics)

        def _write():
            try:
                target = self._step_dir(step)
                if self.use_orbax:
                    self._ocp.save(target, payload, force=True)
                    self._ocp.wait_until_finished()
                else:
                    import shutil

                    from ..core import durable as core_durable

                    # Stage into a FRESH .tmp: a leftover from a killed
                    # worker would otherwise leak its stale files into
                    # the final checkpoint (os.replace moves the whole
                    # directory, garbage included).
                    tmp = target + ".tmp"
                    shutil.rmtree(tmp, ignore_errors=True)
                    os.makedirs(tmp)
                    raw = pickle.dumps(payload)
                    # fsync-then-rename + an integrity manifest inside
                    # the staged dir (the durable commit protocol), so
                    # a torn or bit-flipped state.pkl is rejected at
                    # restore instead of silently unpickled
                    core_durable.atomic_write(
                        os.path.join(tmp, "state.pkl"), raw,
                        detail=f"state.pkl@{step_dir_name(step)}")
                    core_durable.atomic_write(
                        os.path.join(tmp, core_durable.MANIFEST),
                        json.dumps({
                            "files": {"state.pkl": {
                                "sha256": hashlib.sha256(raw).hexdigest(),
                                "bytes": len(raw),
                            }}}, sort_keys=True).encode(),
                        detail=f"manifest@{step_dir_name(step)}")
                    # Overwrite semantics (orbax force=True parity)
                    # WITHOUT the lose-both window: os.replace of a
                    # directory onto an existing non-empty one raises
                    # ENOTEMPTY, and rmtree-then-replace leaves NO
                    # checkpoint if the process dies in between.
                    # Rotate the old step aside, promote the staged
                    # one, then drop the rotated copy — a crash at any
                    # point leaves a loadable step_N or step_N.old.
                    if os.path.exists(target):
                        old = target + ".old"
                        shutil.rmtree(old, ignore_errors=True)
                        os.replace(target, old)
                        os.replace(tmp, target)
                        shutil.rmtree(old, ignore_errors=True)
                    else:
                        os.replace(tmp, target)
                self._gc()
            except BaseException as e:  # surfaced at wait()/next save
                self._error = e

        self._pending = threading.Thread(target=_write, daemon=True)
        self._pending.start()

    def wait(self):
        """Block until the last queued save is durable; re-raises any
        failure from the async writer (a checkpoint that silently
        never landed would lose work on the next crash)."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    def _gc(self):
        if not self.max_to_keep:
            return
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            import shutil

            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # -- read side -----------------------------------------------------
    def all_steps(self) -> List[int]:
        return list_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    @staticmethod
    def _verified(target: str) -> bool:
        """Manifest verification of one step dir; steps written before
        manifests existed (no MANIFEST.json) pass — there is nothing
        recorded to check them against."""
        from ..core import durable as core_durable

        if not os.path.exists(os.path.join(target, core_durable.MANIFEST)):
            return True
        return core_durable.verify_snapshot(target)

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        """Load ``step`` (default: newest); None when no checkpoint.
        ``template`` (a pytree of like-shaped arrays) enables orbax's
        typed restoration.  A step failing manifest verification
        (torn/corrupt) raises when it was requested explicitly and
        falls back to the newest earlier intact step otherwise."""
        explicit = step is not None
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        target = self._step_dir(step)
        if self.use_orbax:
            if template is not None:
                return self._ocp.restore(target, template)
            return self._ocp.restore(target)
        if not os.path.isdir(target) and os.path.isdir(target + ".old"):
            # a save died between rotating the old step aside and
            # promoting the staged one — the rotated copy is the last
            # durable state; put it back
            os.replace(target + ".old", target)
        if not os.path.isdir(target):
            raise FileNotFoundError(
                f"no checkpoint at step {step} under "
                f"{self.directory!r}: neither {step_dir_name(step)} "
                "nor its .old recovery copy exists")
        if not self._verified(target):
            if explicit:
                raise ValueError(
                    f"checkpoint step {step} under {self.directory!r} "
                    "fails manifest verification (torn or corrupt)")
            for s in reversed(self.all_steps()):
                if s >= step:
                    continue
                if self._verified(self._step_dir(s)):
                    print(f"hvtpu.Checkpointer: step {step} fails "
                          f"manifest verification; falling back to "
                          f"step {s}", file=sys.stderr)
                    target = self._step_dir(s)
                    break
            else:
                raise ValueError(
                    f"every checkpoint under {self.directory!r} fails "
                    "manifest verification")
        with open(os.path.join(target, "state.pkl"), "rb") as f:
            return pickle.load(f)


def save_checkpoint(directory: str, step: int, payload: Dict[str, Any],
                    max_to_keep: Optional[int] = None) -> Checkpointer:
    """One-shot convenience: async rank-0 save (returns the
    Checkpointer so callers can ``wait()``)."""
    ckpt = Checkpointer(directory, max_to_keep=max_to_keep)
    ckpt.save(step, payload)
    return ckpt


def restore_checkpoint(directory: str, step: Optional[int] = None,
                       template: Optional[Dict[str, Any]] = None,
                       broadcast: bool = True):
    """Restore on rank 0 and (by default) fan out to every rank via
    ``broadcast_object`` — the reference's restore idiom
    (horovod/torch/functions.py broadcast fanout)."""
    from . import functions as api_functions

    st = core_state.require_init("restore_checkpoint")
    payload = None
    if st.rank == 0:
        payload = Checkpointer(directory).restore(step, template)
    if broadcast and st.size > 1:
        payload = api_functions.broadcast_object(payload, root_rank=0)
    return payload
