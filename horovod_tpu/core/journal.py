"""Per-rank journal of self-authored durable coordination keys.

The coordination KV lives in the coordinator process (rank 0's host);
when that host dies, the elastic driver relaunches the job against a
FRESH, EMPTY KV (elastic/driver.py re-elects the coordinator from the
surviving slots).  Everything the protocols derive from scratch at
init — rendezvous, clock sync, stall heartbeats — rebuilds for free,
but a small set of keys is *history* the new incarnation cannot
recompute: restore-quorum votes, drain accounting, blacklist hints.
Losing them turns one coordinator death into a whole-job loss (the
exact failure PR 15's restore quorum degrades around).

:class:`KeyJournal` closes that hole from the writer's side: each rank
appends its OWN authored keys under the registered durable prefixes to
``<state_dir>/kvjournal/rank<R>.jsonl`` (the driver-provided elastic
state dir — host-local disk that survives the relaunch), and the next
incarnation replays them into the fresh KV before the protocols start.
Journaling rides :class:`~horovod_tpu.core.retry.FencedKV`'s write
path, so a fenced (superseded) rank can never journal — replay only
ever re-publishes keys a then-live writer authored, stamped with the
REPLAYING incarnation's fencing token.

Append-only, last-value-wins: ``record`` appends one JSON line per
write, ``entries`` folds the file newest-wins, ``forget`` appends a
tombstone.  The file is tiny (a handful of votes/hints per rank) and
rewritten compacted whenever it grows past ``_COMPACT_AT`` lines.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, Optional

logger = logging.getLogger("horovod_tpu")

_COMPACT_AT = 1024


class KeyJournal:
    """One rank's durable-key journal under ``state_dir``."""

    def __init__(self, state_dir: str, rank: int = 0):
        self.rank = rank
        self.path = os.path.join(state_dir, "kvjournal",
                                 f"rank{rank}.jsonl")
        self._mem: Dict[str, Optional[str]] = dict(self._load())
        self._lines = len(self._mem)

    # -- write side -----------------------------------------------------
    def record(self, key: str, value: str) -> None:
        """Journal one authored ``key = value`` (last write wins)."""
        self._mem[key] = value
        self._append({"k": key, "v": value})

    def forget(self, key: str) -> None:
        """Tombstone a deleted key so replay does not resurrect it."""
        if key in self._mem:
            self._mem[key] = None
            self._append({"k": key, "v": None})

    def _append(self, rec: dict) -> None:
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            self._lines += 1
            if self._lines > _COMPACT_AT:
                self._compact()
        except OSError:
            logger.warning("kv journal: could not append to %s",
                           self.path, exc_info=True)

    def _compact(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for k, v in self._mem.items():
                f.write(json.dumps({"k": k, "v": v}, sort_keys=True)
                        + "\n")
        os.replace(tmp, self.path)
        self._lines = len(self._mem)

    # -- read side ------------------------------------------------------
    def _load(self) -> Dict[str, Optional[str]]:
        out: Dict[str, Optional[str]] = {}
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        out[rec["k"]] = rec["v"]
                    except (ValueError, KeyError, TypeError):
                        continue  # torn tail line: keep what parsed
        except OSError:
            pass
        return out

    def entries(self) -> Dict[str, str]:
        """Live (non-tombstoned) journaled keys, last value wins."""
        return {k: v for k, v in self._mem.items() if v is not None}

    def __len__(self) -> int:
        return len(self.entries())

    # -- replay ---------------------------------------------------------
    def replay(self, kv, skip_existing: bool = True) -> int:
        """Re-publish this rank's journaled keys into ``kv`` (a fresh
        coordinator after re-election).  With ``skip_existing`` a key
        some live writer already re-authored is left alone — replay
        restores history, never overwrites the present.  Returns the
        number of keys written; per-key failures are logged and
        skipped (replay is best-effort by design: the quorum/drain
        protocols degrade gracefully to recomputing)."""
        replayed = 0
        for key, value in sorted(self.entries().items()):
            if skip_existing:
                try:
                    kv.key_value_try_get(key)
                    continue
                except Exception:
                    pass  # absent (or unreadable): replay it
            try:
                kv.key_value_set(key, value)
                replayed += 1
            except Exception:
                logger.warning("kv journal: replay of %r failed", key,
                               exc_info=True)
        return replayed

    def clear(self) -> None:
        self._mem.clear()
        self._lines = 0
        try:
            os.unlink(self.path)
        except OSError:
            pass


# -- process-wide journal -----------------------------------------------
# All durable-key writers in one process (drain coordinator, restore
# quorum) share a single per-rank journal file so one replay covers
# everything this rank authored.  Keyed off the driver-provided elastic
# state dir; absent that (non-elastic runs, unit tests) there is
# nothing durable to journal into and callers get None.

_default: Optional[KeyJournal] = None


def default_journal(rank: Optional[int] = None) -> Optional[KeyJournal]:
    """The process-wide :class:`KeyJournal` under
    ``HVTPU_ELASTIC_STATE_DIR``, or None when no state dir is set."""
    global _default
    state_dir = os.environ.get("HVTPU_ELASTIC_STATE_DIR")
    if not state_dir:
        return None
    r = int(rank or 0)
    if _default is None or (rank is not None and _default.rank != r):
        _default = KeyJournal(state_dir, rank=r)
    return _default


def reset_default() -> None:
    """Drop the cached process-wide journal (tests / re-init)."""
    global _default
    _default = None
