"""Eager API surface tests (single-process world, P=1 semantics) plus
async-handle behavior — parity targets: horovod/torch/mpi_ops.py eager
ops and handle_manager synchronize/poll.

Multi-process eager behavior is covered by the runner-launched tests
(test_multiprocess.py) which spawn real worker processes, the analog of
the reference's horovodrun-под tests.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu


class TestEagerSingleProcess:
    def test_allreduce_identity(self, hvt):
        x = jnp.arange(6.0).reshape(2, 3)
        out = hvt.allreduce(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_allreduce_scales(self, hvt):
        x = jnp.ones((4,))
        out = hvt.allreduce(x, prescale_factor=2.0, postscale_factor=3.0)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), 6.0))

    def test_grouped_allreduce(self, hvt):
        outs = hvt.grouped_allreduce([jnp.ones((2,)), jnp.full((3,), 2.0)])
        assert len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[1]), np.full((3,), 2.0))

    def test_allgather(self, hvt):
        x = jnp.ones((3, 2))
        out = hvt.allgather(x)
        assert out.shape == (3, 2)

    def test_broadcast(self, hvt):
        x = jnp.arange(4.0)
        out = hvt.broadcast(x, root_rank=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_alltoall_bare_return_without_splits(self, hvt):
        # reference convention: no splits → bare tensor
        x = jnp.arange(6.0).reshape(6, 1)
        out = hvt.alltoall(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_alltoall_tuple_return_with_splits(self, hvt):
        x = jnp.arange(6.0).reshape(6, 1)
        out, splits = hvt.alltoall(x, splits=[6])
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        assert np.asarray(splits).tolist() == [6]

    def test_reducescatter(self, hvt):
        x = jnp.ones((4, 2))
        out = hvt.reducescatter(x)
        assert out.shape == (4, 2)

    def test_barrier_and_join(self, hvt):
        hvt.barrier()
        assert hvt.join() == 0

    def test_async_and_synchronize(self, hvt):
        h = hvt.allreduce_async(jnp.ones((2,)))
        # Truly async now (reference semantics): poll flips to True once
        # the background cycle completes the op; synchronize blocks.
        out = hvt.synchronize(h)
        assert hvt.poll(h)  # completed handles poll True
        np.testing.assert_allclose(np.asarray(out), np.ones((2,)))
        with pytest.raises(ValueError):
            hvt.synchronize(h)  # double-sync of same handle


class TestStateDistribution:
    def test_broadcast_parameters_roundtrip(self, hvt):
        params = {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}
        out = hvt.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones((2, 2)))

    def test_broadcast_object(self, hvt):
        obj = {"epoch": 3, "names": ["a", "b"]}
        assert hvt.broadcast_object(obj, root_rank=0) == obj

    def test_allgather_object(self, hvt):
        assert hvt.allgather_object({"r": 0}) == [{"r": 0}]


class TestGroupedVariants:
    """Grouped allgather / reducescatter (newer-upstream surface)."""

    def test_grouped_allgather_sync_and_async(self, hvt):
        import jax.numpy as jnp
        import numpy as np

        outs = hvt.grouped_allgather([jnp.ones((2, 2)), jnp.zeros((3,))])
        assert [tuple(o.shape) for o in outs] == [(2, 2), (3,)]
        handles = hvt.grouped_allgather_async(
            [jnp.ones((2,)), jnp.full((1,), 5.0)], names=["ga1", "ga2"]
        )
        res = [hvt.synchronize(h) for h in handles]
        np.testing.assert_allclose(np.asarray(res[1]), [5.0])

    def test_grouped_reducescatter_sync_and_async(self, hvt):
        import jax.numpy as jnp
        import numpy as np

        outs = hvt.grouped_reducescatter(
            [jnp.ones((4, 2)), jnp.full((2,), 3.0)], op=hvt.Sum
        )
        assert [tuple(o.shape) for o in outs] == [(4, 2), (2,)]
        handles = hvt.grouped_reducescatter_async(
            [jnp.ones((2,)), jnp.ones((4,))], names=["rs1", "rs2"],
            op=hvt.Sum,
        )
        res = [hvt.synchronize(h) for h in handles]
        np.testing.assert_allclose(np.asarray(res[0]), [1.0, 1.0])


class TestNegotiationTimeline:
    def test_negotiate_phase_recorded(self, hvt, tmp_path):
        import json

        import jax.numpy as jnp

        path = str(tmp_path / "tl.json")
        hvt.start_timeline(path)
        h = hvt.allreduce_async(jnp.ones(4), name="tl_t", op=hvt.Sum)
        hvt.synchronize(h)
        hvt.stop_timeline()
        with open(path) as f:
            content = f.read()
        # Chrome-trace array may lack the closing bracket mid-stream
        if not content.rstrip().endswith("]"):
            content = content.rstrip().rstrip(",") + "]"
        events = json.loads(content)
        negotiate = [e for e in events
                     if e.get("name") == "NEGOTIATE_ALLREDUCE"]
        assert any(e.get("ph") == "B" for e in negotiate)
        assert any(e.get("ph") == "E" for e in negotiate)

    def test_timeline_attach_to_live_controller(self, hvt, tmp_path):
        """start_timeline AFTER the controller exists must still record
        NEGOTIATE spans (the controller's timeline ref is updated)."""
        import json

        import jax.numpy as jnp

        # create the controller BEFORE the timeline starts
        hvt.synchronize(hvt.allreduce_async(jnp.ones(2), name="pre"))
        path = str(tmp_path / "tl2.json")
        hvt.start_timeline(path)
        hvt.synchronize(hvt.allreduce_async(jnp.ones(2), name="post"))
        hvt.stop_timeline()
        with open(path) as f:
            content = f.read()
        if not content.rstrip().endswith("]"):
            content = content.rstrip().rstrip(",") + "]"
        events = json.loads(content)
        assert any(e.get("name") == "NEGOTIATE_ALLREDUCE"
                   and e.get("args", {}).get("tensor") == "post"
                   for e in events)


def test_adasum_rejects_int8_compression(hvt):
    """The eager path must enforce the same int8+Adasum guard as spmd
    (the hierarchical Adasum kernel would otherwise silently run dot
    products over per-rank block-scaled codes)."""
    import jax.numpy as jnp
    import pytest as _pytest

    from horovod_tpu.comm.compression import Compression

    with _pytest.raises(ValueError, match="Adasum"):
        hvt.allreduce(jnp.ones(8), op=hvt.Adasum,
                      compression=Compression.int8)


def test_broadcast_parameters_updates_mutable_containers(hvt):
    """Reference ergonomics: statement-style
    hvd.broadcast_parameters(state_dict) must take effect — leaves in
    mutable containers are updated in place (the functional return is
    also complete).  numpy leaves make this non-vacuous: broadcast
    returns NEW jax arrays, so without the write-back the containers
    would still hold the numpy originals."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    params = {"w": np.zeros((4,), np.float32),
              "inner": {"b": np.ones((2,), np.float32)},
              "lst": [np.full((3,), 2.0, np.float32)],
              "tup": ({"t": np.full((2,), 5.0, np.float32)},)}
    inner_tuple_dict = params["tup"][0]
    ret = hvt.broadcast_parameters(params, root_rank=0)
    assert params["w"] is ret["w"]
    assert isinstance(params["w"], jax.Array)
    assert params["inner"]["b"] is ret["inner"]["b"]
    assert params["lst"][0] is ret["lst"][0]
    # mutable dict held from inside an (immutable) tuple is updated too
    assert isinstance(inner_tuple_dict["t"], jax.Array)
    np.testing.assert_array_equal(np.asarray(inner_tuple_dict["t"]),
                                  np.full((2,), 5.0))

    # broadcast_optimizer_state gets the same ergonomics
    opt_state = {"m": np.zeros((3,), np.float32), "step": 7}
    ret2 = hvt.broadcast_optimizer_state(opt_state, root_rank=0)
    assert opt_state["m"] is ret2["m"]
    assert isinstance(opt_state["m"], jax.Array)


def test_broadcast_parameters_fuses_one_collective(hvt, monkeypatch):
    """N leaves must ride ONE fused byte-buffer broadcast (the torch
    frontend's FusionBufferManager-style fast path), not N per-leaf
    collectives/compilations."""
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.comm import eager as eager_comm

    calls = []
    real = eager_comm.broadcast

    def spy(tensor, **kw):
        calls.append(np.asarray(tensor).nbytes)
        return real(tensor, **kw)

    monkeypatch.setattr(eager_comm, "broadcast", spy)
    import horovod_tpu.api.functions as fns

    params = {"w": jnp.ones((10, 3)), "b": jnp.zeros((7,)),
              "s": jnp.full((2,), 2.0, jnp.bfloat16),
              "scalar": jnp.float32(4.0)}
    out = fns.broadcast_parameters(params, root_rank=0)
    assert len(calls) == 1
    assert calls[0] == 10 * 3 * 4 + 7 * 4 + 2 * 2 + 4
    assert out["scalar"].shape == () and float(out["scalar"]) == 4.0
    np.testing.assert_array_equal(np.asarray(out["w"]), np.ones((10, 3)))
    assert out["s"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["s"].astype(jnp.float32)), np.full((2,), 2.0))


def test_broadcast_optimizer_state_preserves_scalar_types(hvt):
    """Reference parity: Python scalar state entries come back as
    Python scalars (torch's version casts back after the wire trip) —
    the in-place write-back must not clobber the caller's dict with
    un-serializable 0-d Arrays."""
    import json

    import numpy as np

    opt = {"step": 7, "lr": 0.01, "nesterov": True,
           "m": np.zeros((3,), np.float32)}
    ret = hvt.broadcast_optimizer_state(opt, root_rank=0)
    assert type(opt["step"]) is int and opt["step"] == 7
    assert type(opt["lr"]) is float and abs(opt["lr"] - 0.01) < 1e-9
    assert type(opt["nesterov"]) is bool
    json.dumps({k: v for k, v in opt.items() if k != "m"})  # serializable
    assert type(ret["step"]) is int
