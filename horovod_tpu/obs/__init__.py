from . import anomaly
from . import flight
from . import metrics
from . import profile
from . import stepprof
from .autotune import Autotuner
from .metrics import REGISTRY as metrics_registry
from .profile import (device_time_ms, load_profile, op_summary,
                      plane_names, trace)
from .timeline import Timeline, start_jax_profiler, stop_jax_profiler

__all__ = [
    "Autotuner",
    "Timeline",
    "start_jax_profiler",
    "stop_jax_profiler",
    # device-trace profiling (obs/profile.py)
    "profile",
    "trace",
    "op_summary",
    "device_time_ms",
    "plane_names",
    "load_profile",
    # step-level overlap profiler (obs/stepprof.py)
    "stepprof",
    # flight recorder + postmortems (obs/flight.py)
    "flight",
    # online anomaly detection + incidents (obs/anomaly.py)
    "anomaly",
    # metrics registry + Prometheus exposition (obs/metrics.py)
    "metrics",
    "metrics_registry",
]
