"""TorchEstimator — Spark-style estimator over the torch frontend.

Parity surface: ``horovod/spark/torch/estimator.py``
(``TorchEstimator``, ``TorchModel``) and ``.../torch/remote.py``
(``RemoteTrainer``): fit() ships (model, optimizer, loss) to every
rank, trains with the Horovod idiom — broadcast initial state, wrap
the optimizer, shard rows per rank — checkpoints through the Store,
and returns a TorchModel whose transform() runs the trained module.

TPU-native notes: ranks are hvtpurun worker processes whose gradient
allreduce rides the JAX/XLA collective fabric via
``horovod_tpu.torch.DistributedOptimizer`` (DLPack zero-copy both
ways); data arrives as the Store's materialized npz (common.data), not
Petastorm.
"""

from __future__ import annotations

import copy
import io
import json
import os
from typing import Any, Dict, List

from ..common.data import TRAIN_NPZ, VAL_NPZ, load_shard
from ..common.estimator import (
    HorovodEstimator,
    HorovodModel,
    resolve_compression,
)

CHECKPOINT_FILE = "checkpoint.pt"


def _epoch_batches(n: int, batch_size: int, n_batches: int, rng):
    """Exactly ``n_batches`` index batches from this rank's ``n`` rows,
    wrapping the (shuffled) permutation when n < n_batches*batch_size.

    The batch COUNT must be identical on every rank — each batch (or
    each ``backward_passes_per_step`` group) issues collective gradient
    allreduces, and strided shards differ by up to one row, which can
    otherwise flip ceil(n/batch) on one rank and deadlock the epoch.
    The count is therefore derived from the GLOBAL row count upstream,
    and wrapping keeps a short shard contributing full batches."""
    import numpy as np

    perm = rng.permutation(n) if rng is not None else np.arange(n)
    idxs = np.resize(perm, n_batches * batch_size)
    for s in range(n_batches):
        yield idxs[s * batch_size:(s + 1) * batch_size]


def _torch_trainer(spec: Dict[str, Any]):
    """Per-rank training loop (reference: torch/remote.py
    RemoteTrainer.train) — module-level so the launcher channel pickles
    it by reference."""
    import cloudpickle
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd
    from ..common.store import FilesystemStore

    hvd.init()
    p = spec["params"]
    seed = p.get("random_seed")
    if seed is not None:
        torch.manual_seed(seed + hvd.rank())
        np.random.seed(seed + hvd.rank())

    model, optimizer, loss_fns, metric_fns, transformation_fn = \
        cloudpickle.loads(spec["train_blob"])
    store = FilesystemStore(spec["store_prefix"])
    run_id = spec["run_id"]

    shard = load_shard(store.get_train_data_path(), TRAIN_NPZ,
                       hvd.rank(), hvd.size())
    val_shard = None
    # every rank must have val rows (rows[r::size] nonempty iff
    # r < n_val) or none may evaluate: the per-epoch val_loss
    # allreduce is collective
    if 0 < spec["n_val"] < hvd.size() and hvd.rank() == 0:
        import logging

        logging.getLogger("horovod_tpu").warning(
            "validation disabled: %d validation rows cannot cover %d "
            "ranks (every rank needs >=1 row or the val_loss allreduce "
            "desyncs); grow the validation split or reduce num_proc",
            spec["n_val"], hvd.size())
    if spec["n_val"] >= hvd.size():
        val_shard = load_shard(store.get_val_data_path(), VAL_NPZ,
                               hvd.rank(), hvd.size())

    feature_cols = p["feature_cols"]
    label_cols = p["label_cols"]

    def tensors(cols, source):
        return [torch.from_numpy(np.ascontiguousarray(source[c]))
                for c in cols]

    features = tensors(feature_cols, shard)
    labels = tensors(label_cols, shard)
    # Sample weights (parity: sample_weight_col — the reference's
    # torch trainer passes the weight batch as the loss callable's
    # THIRD argument; loss fns must accept (output, label, weight))
    sw_col = p.get("sample_weight_col")
    weights = (torch.from_numpy(np.ascontiguousarray(
        shard[sw_col]).astype(np.float32)) if sw_col else None)
    # transformation_fn applies to the rank's (features, labels) at
    # data load — one contract shared with the keras trainer, so the
    # same hook behaves identically under either estimator; training,
    # per-epoch metrics and validation all see the transformed data
    if transformation_fn is not None:
        features, labels = transformation_fn(features, labels)
    val_features = val_labels = val_weights = None
    if val_shard is not None:
        val_features = tensors(feature_cols, val_shard)
        val_labels = tensors(label_cols, val_shard)
        if sw_col:
            val_weights = torch.from_numpy(np.ascontiguousarray(
                val_shard[sw_col]).astype(np.float32))
        if transformation_fn is not None:
            val_features, val_labels = transformation_fn(
                val_features, val_labels)

    # Resume (parity: the reference estimator's checkpoint-resume on
    # refit): rank 0 loads the run's latest Store checkpoint; the
    # broadcast below propagates it to every rank.  Model AND
    # optimizer state resume, so momentum etc. continue seamlessly.
    if p.get("resume_from_checkpoint") and hvd.rank() == 0:
        ckpt_path = os.path.join(
            store.get_checkpoint_path(run_id), CHECKPOINT_FILE)
        if os.path.exists(ckpt_path):
            state = torch.load(ckpt_path, weights_only=True)
            model.load_state_dict(state["model"])
            optimizer.load_state_dict(state["optimizer"])

    # Horovod idiom: everyone starts from rank 0's state, gradients
    # are averaged in the wrapped optimizer.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    # bps derived ONCE: the optimizer's aggregation period and the
    # loop's step()/zero_grad() cadence must never diverge
    bps = p.get("backward_passes_per_step") or 1
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=resolve_compression(
            hvd, p.get("gradient_compression") or p.get("compression")),
        backward_passes_per_step=bps)

    # per-output loss scaling (reference: loss_weights); None = 1.0
    loss_weights = p.get("loss_weights")
    if loss_weights is not None and len(loss_weights) != len(loss_fns):
        raise ValueError(
            f"loss_weights has {len(loss_weights)} entries for "
            f"{len(loss_fns)} loss function(s)")

    def forward_loss(feat_batch, label_batch, weight_batch=None):
        outputs = model(*feat_batch)
        if not isinstance(outputs, (tuple, list)):
            outputs = [outputs]
        losses = [
            fn(o, y) if weight_batch is None else fn(o, y, weight_batch)
            for fn, o, y in zip(loss_fns, outputs, label_batch)
        ]
        if loss_weights is not None:
            losses = [w * l for w, l in zip(loss_weights, losses)]
        return outputs, sum(losses)

    batch_size = p["batch_size"]
    n = len(features[0])
    if n == 0:
        raise ValueError(
            f"rank {hvd.rank()}'s training shard is empty "
            f"({spec['n_train']} rows over {hvd.size()} ranks); "
            "reduce num_proc or provide more data")
    # rank-CONSISTENT batch count from the global row count (see
    # _epoch_batches): every rank has at least n_train//size rows
    min_rows = max(1, spec["n_train"] // hvd.size())
    n_batches = -(-min_rows // batch_size)  # ceil
    if p.get("train_steps_per_epoch") is not None:
        n_batches = min(n_batches, p["train_steps_per_epoch"])
    # whole aggregation groups only: step() fires after exactly bps
    # backward passes; a cap below one group is a config error, not a
    # silent overrun of the user's explicit limit
    if n_batches < bps:
        raise ValueError(
            f"train_steps_per_epoch/row budget gives {n_batches} "
            f"batch(es) per epoch, fewer than "
            f"backward_passes_per_step={bps}: no optimizer step could "
            "ever fire")
    if n_batches % bps and hvd.rank() == 0:
        import logging

        logging.getLogger("horovod_tpu").warning(
            "batches per epoch rounded %d -> %d to form whole "
            "backward_passes_per_step=%d groups",
            n_batches, n_batches // bps * bps, bps)
    n_batches = n_batches // bps * bps
    history: Dict[str, List[float]] = {"loss": []}
    ckpt_dir = store.get_checkpoint_path(run_id)

    for epoch in range(p["epochs"]):
        model.train()
        rng = (np.random.RandomState(
            (0 if seed is None else seed) * 1000 + epoch + hvd.rank())
            if p.get("shuffle", True) else None)
        epoch_loss, steps = 0.0, 0
        optimizer.zero_grad()
        for s, idx in enumerate(
                _epoch_batches(n, batch_size, n_batches, rng)):
            fb = [f[idx] for f in features]
            lb = [y[idx] for y in labels]
            wb = weights[idx] if weights is not None else None
            _, loss = forward_loss(fb, lb, wb)
            loss.backward()
            if (s + 1) % bps == 0:
                optimizer.step()
                optimizer.zero_grad()
            epoch_loss += float(loss.detach())
            steps += 1
        # epoch metrics are averaged over ranks, like the reference's
        # metric averaging hooks
        avg = hvd.allreduce(
            torch.tensor([epoch_loss / max(steps, 1)]), name="epoch_loss")
        history["loss"].append(float(avg[0]))
        if metric_fns:
            with torch.no_grad():
                outputs = model(*features)
            if not isinstance(outputs, (tuple, list)):
                outputs = [outputs]
            for i, mfn in enumerate(metric_fns):
                name = getattr(mfn, "__name__", f"metric_{i}")
                with torch.no_grad():
                    m = mfn(outputs[0] if len(outputs) == 1 else outputs,
                            labels[0] if len(labels) == 1 else labels)
                mv = hvd.allreduce(torch.as_tensor([float(m)]),
                                   name=f"metric_{name}")
                history.setdefault(name, []).append(float(mv[0]))
        if val_features is not None:
            model.eval()
            with torch.no_grad():
                _, vloss = forward_loss(val_features, val_labels,
                                        val_weights)
            vavg = hvd.allreduce(
                torch.tensor([float(vloss)]), name="val_loss")
            history.setdefault("val_loss", []).append(float(vavg[0]))
        if hvd.rank() == 0:
            os.makedirs(ckpt_dir, exist_ok=True)
            tmp = os.path.join(ckpt_dir, CHECKPOINT_FILE + ".tmp")
            torch.save({"model": model.state_dict(),
                        "optimizer": optimizer.state_dict(),
                        "epoch": epoch}, tmp)
            os.replace(tmp, os.path.join(ckpt_dir, CHECKPOINT_FILE))

    result: Dict[str, Any] = {"history": history}
    if hvd.rank() == 0:
        store.write_text(
            os.path.join(store.get_logs_path(run_id), "history.json"),
            json.dumps(history))
        buf = io.BytesIO()
        torch.save(model.state_dict(), buf)
        result["state_dict"] = buf.getvalue()
    hvd.shutdown()
    return result


class TorchEstimator(HorovodEstimator):
    """Reference-shaped params: ``model`` (nn.Module), ``optimizer``
    (constructed against the model's parameters, exactly as the
    reference requires), ``loss`` (callable or list matched to
    label_cols / multi-output models)."""

    _param_defs = {
        "optimizer": None,
        "input_shapes": None,   # accepted for source compat
        "backward_passes_per_step": 1,
    }

    def _check_params(self):
        super()._check_params()
        if self.getOptimizer() is None:
            raise ValueError(
                "optimizer param is required and must be constructed "
                "against the model's parameters "
                "(torch.optim.SGD(model.parameters(), ...))")
        if self.getLoss() is None:
            raise ValueError("loss param is required (callable or list)")
        if self.getSampleWeightCol() is not None:
            # weight batches ride the loss callable's THIRD argument
            # (reference contract); fail at fit() on the driver, not
            # with a confusing TypeError deep inside a worker rank
            import inspect

            loss = self.getLoss()
            fns = list(loss) if isinstance(loss, (list, tuple)) \
                else [loss]
            weight_names = {"weight", "weights", "sample_weight",
                            "sample_weights", "sw", "w"}
            for fn in fns:
                # nn.Module.__call__ is (*args, **kwargs): the real
                # arity lives on forward
                target = getattr(fn, "forward", fn)
                try:
                    sig = inspect.signature(target)
                except (TypeError, ValueError):
                    continue  # uninspectable callable: trust the user
                params = list(sig.parameters.values())
                if any(q.kind == q.VAR_POSITIONAL for q in params):
                    continue
                positional = [
                    q for q in params
                    if q.kind in (q.POSITIONAL_ONLY,
                                  q.POSITIONAL_OR_KEYWORD)]
                # the weight batch binds to the THIRD positional slot;
                # that slot must clearly be a weight: either required
                # (no default) or weight-named.  This rejects losses
                # like F.mse_loss, whose third slot is the defaulted
                # legacy `size_average` — the weight tensor would bind
                # there and crash (or silently train unweighted for a
                # defaulted `eps`-style third arg).
                third_ok = len(positional) >= 3 and (
                    positional[2].default is positional[2].empty
                    or positional[2].name.lower() in weight_names)
                if not third_ok:
                    raise ValueError(
                        f"sample_weight_col is set but loss "
                        f"{getattr(fn, '__name__', fn)!r} does not "
                        "take a sample-weight third argument — it "
                        "must accept (output, label, sample_weight) "
                        "with the third parameter required or named "
                        "like a weight")
                third = positional[2]
                if (third.default is third.empty
                        and third.name.lower() not in weight_names):
                    # A required third arg passes the gate, but a
                    # non-weight-looking name (focal's `gamma`, say)
                    # probably means the weight batch is about to bind
                    # to a hyperparameter and train silently wrong —
                    # say so, naming the parameter.  HVTPU_SPARK_STRICT
                    # upgrades the warning to a hard error for
                    # pipelines that would rather fail at fit() than
                    # risk a silently misweighted model.
                    msg = (
                        f"sample_weight_col is set and loss "
                        f"{getattr(fn, '__name__', fn)!r} will receive "
                        f"the per-sample weight batch as its third "
                        f"positional argument {third.name!r}, which "
                        "does not look like a weight parameter — if "
                        f"{third.name!r} is a hyperparameter, bind it "
                        "with functools.partial and accept "
                        "(output, label, sample_weight) instead")
                    strict = os.environ.get(
                        "HVTPU_SPARK_STRICT", "").lower()
                    if strict not in ("", "0", "false", "no"):
                        raise ValueError(
                            msg + " (raised because HVTPU_SPARK_STRICT "
                            "is set; unset it to downgrade this to a "
                            "warning)")
                    import warnings

                    warnings.warn(
                        msg + " (set HVTPU_SPARK_STRICT=1 to make this "
                        "an error)",
                        stacklevel=2)
        lw = self.getLossWeights()
        if lw is not None:
            loss = self.getLoss()
            n_fns = len(loss) if isinstance(loss, (list, tuple)) else 1
            if len(lw) != n_fns:
                raise ValueError(
                    f"loss_weights has {len(lw)} entries for {n_fns} "
                    "loss function(s)")

    def _serialize_training_spec(self) -> Dict[str, Any]:
        import cloudpickle

        loss = self.getLoss()
        loss_fns = list(loss) if isinstance(loss, (list, tuple)) \
            else [loss]
        # one blob: model + optimizer pickled TOGETHER so the
        # optimizer's parameter references stay identical to the
        # model's parameters after unpickling
        blob = cloudpickle.dumps((
            self.getModel(), self.getOptimizer(), loss_fns,
            list(self.getMetrics() or []), self.getTransformationFn()))
        return {"train_blob": blob}

    def _remote_trainer(self):
        return _torch_trainer

    def _create_model(self, rank_results, run_id, store):
        import torch

        state = next(r["state_dict"] for r in rank_results
                     if "state_dict" in r)
        trained = copy.deepcopy(self.getModel())
        trained.load_state_dict(
            torch.load(io.BytesIO(state), weights_only=True))
        trained.eval()
        return TorchModel(
            model=trained,
            feature_cols=list(self.getFeatureCols()),
            label_cols=list(self.getLabelCols()),
            output_cols=self.getOutputCols(),
            run_id=run_id, store=store,
            history=rank_results[0]["history"],
            batch_size=self.getBatchSize(),
        )


class TorchModel(HorovodModel):
    def _predict_columns(self, features):
        import numpy as np
        import torch

        model = self.getModel()
        model.eval()
        cols = [torch.from_numpy(np.ascontiguousarray(features[c]))
                for c in self.getFeatureCols()]
        outs: List[List[Any]] = None
        bs = self.getBatchSize()
        n = len(cols[0])
        with torch.no_grad():
            for lo in range(0, n, bs):
                batch = [c[lo:lo + bs] for c in cols]
                o = model(*batch)
                if not isinstance(o, (tuple, list)):
                    o = [o]
                if outs is None:
                    outs = [[] for _ in o]
                for acc, piece in zip(outs, o):
                    acc.append(piece.numpy())
        merged = [np.concatenate(a) for a in (outs or [])]
        # 1-col outputs flatten so they fit a DataFrame column; wider
        # outputs stay 2-D (object column on pandas assign)
        return [m.reshape(-1) if m.ndim == 2 and m.shape[1] == 1
                else (list(m) if m.ndim > 1 else m) for m in merged]
