"""End-to-end CLI launches: ``hvtpurun -np N python examples/...`` as a
real subprocess invocation — the reference's `horovodrun -np 2 python
train.py` acceptance path (VERDICT round-1 task 1 'done when')."""

import os
import subprocess
import sys

import pytest

import horovod_tpu

pytestmark = pytest.mark.multiprocess

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))


# jaxlib's gloo CPU transport occasionally drops a connection under
# parallel localhost load (a rank SIGSEGVs; peers report "Connection
# closed by peer").  That race lives below this framework — retry the
# whole launch (core/retry.py's named gloo-teardown policy) so the
# acceptance assertions still gate every example, but an infra crash
# alone doesn't flake CI.
from horovod_tpu.core import retry as core_retry


def _gloo_race(res):
    return (res.returncode != 0
            and core_retry.is_gloo_infra_error(res.stdout + res.stderr))


def _hvtpurun(args, timeout=300):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return core_retry.call(
        core_retry.gloo_teardown_policy(max_attempts=3,
                                        retry_result=_gloo_race),
        lambda: subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner"] + args,
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_REPO,
        ))


def test_cli_jax_mnist_2proc():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable, os.path.join(_REPO, "examples", "train_mnist.py"),
        "--epochs", "1", "--train-size", "256", "--batch-size", "64",
    ])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks" in res.stdout


def test_cli_torch_mnist_2proc():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable, os.path.join(_REPO, "examples", "pytorch_mnist.py"),
        "--epochs", "1", "--train-size", "256", "--batch-size", "64",
    ])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_cli_tf_keras_mnist_2proc():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable,
        os.path.join(_REPO, "examples", "tensorflow2_keras_mnist.py"),
        "--epochs", "1", "--train-size", "256", "--batch-size", "64",
    ], timeout=420)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_cli_torch_adasum_2proc():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable,
        os.path.join(_REPO, "examples", "pytorch_mnist_adasum.py"),
        "--epochs", "1", "--train-size", "256", "--batch-size", "64",
    ])
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_cli_tf2_custom_loop_2proc():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable,
        os.path.join(_REPO, "examples", "tensorflow2_mnist.py"),
        "--steps", "8",
    ], timeout=420)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


def _static_discovery(tmp_path, slots=2):
    from conftest import make_discovery_script

    _hosts, script = make_discovery_script(tmp_path,
                                           f"localhost:{slots}")
    return script


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_cli_torch_elastic_example(tmp_path):
    res = _hvtpurun([
        "--host-discovery-script", _static_discovery(tmp_path),
        "--min-np", "2", "--cpu-devices", "1", "--",
        sys.executable,
        os.path.join(_REPO, "examples", "pytorch_mnist_elastic.py"),
    ], timeout=420)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


@pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
def test_cli_keras_elastic_example(tmp_path):
    res = _hvtpurun([
        "--host-discovery-script", _static_discovery(tmp_path),
        "--min-np", "2", "--cpu-devices", "1", "--",
        sys.executable,
        os.path.join(_REPO, "examples",
                     "tensorflow2_keras_mnist_elastic.py"),
    ], timeout=420)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ranks consistent (2 ranks)" in res.stdout


def test_cli_failure_exit_code():
    res = _hvtpurun([
        "-np", "2", "--cpu-devices", "1", "--",
        sys.executable, "-c", "import sys, os; "
        "sys.exit(3 if os.environ['HVTPU_RANK'] == '1' else 0)",
    ])
    assert res.returncode == 3
    assert "rank 1 exited with code 3" in res.stderr


def test_hybrid_transformer_example():
    """The post-parity parallel-layer example must run (single process,
    8 virtual CPU devices, dp x pp x tp + sp + ep)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "transformer_hybrid.py"),
         "--steps", "4", "--d-model", "32", "--layers", "2"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "hybrid-parallel training OK" in res.stdout
