"""bench.py's round-over-round guards: the regression floors
(VERDICT r4 #4 — BENCH_MODELS.json bar.floors fail the run on a
deliberate 3% slowdown) and the embedded metrics snapshot (every bench
JSON line must carry the condensed registry snapshot so BENCH_*
trajectories stay schema-comparable on wire-bytes and cycle stats)."""

import json
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


@pytest.fixture
def bench():
    import importlib

    import bench as bench_mod

    return importlib.reload(bench_mod)


class TestRegressionFloor:
    def test_floors_recorded_for_all_models(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            bar = json.load(f)["bar"]
        assert set(bar["floors"]) == set(bench.MODELS)
        assert 0 < bar["tolerance"] < 0.1

    def test_within_tolerance_passes(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            floors = json.load(f)["bar"]["floors"]
        for model, floor in floors.items():
            assert bench.check_regression_floor(
                model, floor * 0.99, _ROOT) is None
            assert bench.check_regression_floor(
                model, floor * 1.10, _ROOT) is None

    def test_three_percent_slowdown_fails(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            floors = json.load(f)["bar"]["floors"]
        for model, floor in floors.items():
            err = bench.check_regression_floor(model, floor * 0.97, _ROOT)
            assert err is not None and "REGRESSION" in err, model
            assert model in err

    def test_unknown_model_or_missing_file_is_silent(self, bench, tmp_path):
        assert bench.check_regression_floor("nosuch", 1.0, _ROOT) is None
        assert bench.check_regression_floor(
            "resnet50", 1.0, str(tmp_path)) is None


class TestMetricsEmbedding:
    """The bench JSON schema REQUIRES the embedded metrics snapshot —
    future bench rounds must stay comparable on wire bytes and cycle
    stats, not just img/s."""

    def test_report_always_embeds_metrics(self, bench):
        report = bench.build_report(metric="m", value=1.0, unit="u")
        assert "metrics" in report
        for key in bench.REQUIRED_METRIC_KEYS:
            assert key in report["metrics"], key
        # the report must stay a single JSON-serializable line
        json.dumps(report)

    def test_condensed_schema_shapes(self, bench):
        from horovod_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.MetricsRegistry()
        reg.counter("hvtpu_wire_bytes_total").inc(4096)
        reg.histogram("hvtpu_controller_cycle_seconds",
                      buckets=[0.1]).observe(0.05)
        out = bench.condense_metrics(reg.snapshot())
        assert out["hvtpu_wire_bytes_total"] == 4096
        cell = out["hvtpu_controller_cycle_seconds"]
        assert cell["count"] == 1 and cell["sum"] == 0.05
        # untouched required families appear as zeros, never missing
        assert out["hvtpu_allreduce_total"] == 0
        assert out["hvtpu_optimizer_steps_total"] == 0

    def test_required_keys_cover_wire_and_cycles(self, bench):
        required = set(bench.REQUIRED_METRIC_KEYS)
        assert "hvtpu_wire_bytes_total" in required
        assert "hvtpu_controller_cycle_seconds" in required
        assert "hvtpu_optimizer_steps_total" in required
        # PR 7: straggler signal rides in every bench line
        assert "hvtpu_collective_arrival_skew_seconds" in required

    def test_report_embeds_arrival_skew_summary(self, bench):
        report = bench.build_report(metric="m", value=1.0, unit="u")
        skew = report["arrival_skew"]
        assert set(skew) == {"collectives", "mean_seconds"}
        # 1-proc run: no multi-rank collectives, schema still stable
        assert skew["collectives"] == report["metrics"][
            "hvtpu_collective_arrival_skew_seconds"]["count"]
        json.dumps(report)

    def test_required_keys_cover_data_pipeline(self, bench):
        # PR 9: input-pipeline counters ride in every bench line
        required = set(bench.REQUIRED_METRIC_KEYS)
        assert "hvtpu_data_wait_seconds" in required
        assert "hvtpu_data_batches_delivered_total" in required
        assert "hvtpu_data_samples_delivered_total" in required

    def test_required_keys_cover_durable_state_plane(self, bench):
        required = set(bench.REQUIRED_METRIC_KEYS)
        assert {"hvtpu_ckpt_commit_seconds",
                "hvtpu_ckpt_bytes_written_total",
                "hvtpu_ckpt_verify_failures_total",
                "hvtpu_ckpt_restore_quorum_rounds_total"} <= required
        # histogram condenses to {count, sum}; counters to scalars
        m = bench.condense_metrics({})
        assert m["hvtpu_ckpt_commit_seconds"] == {"count": 0,
                                                 "sum": 0.0}
        assert m["hvtpu_ckpt_verify_failures_total"] == 0

    def test_report_embeds_data_stall_row(self, bench):
        report = bench.build_report(metric="m", value=1.0, unit="u",
                                    elapsed_seconds=10.0)
        stall = report["data_stall"]
        assert set(stall) == {"batches", "wait_seconds",
                              "stall_fraction"}
        assert stall["batches"] == report["metrics"][
            "hvtpu_data_wait_seconds"]["count"]
        # derived against the caller's wall time; null without it
        assert stall["stall_fraction"] == pytest.approx(
            stall["wait_seconds"] / 10.0)
        no_elapsed = bench.build_report(metric="m", value=1.0, unit="u")
        assert no_elapsed["data_stall"]["stall_fraction"] is None
        json.dumps(report)


class TestOverlapSchema:
    """PR 12: the measured overlap/MFU columns ride in every bench
    line, distinguish measured-zero from never-measured, and the
    recorded BENCH_MODELS rows carry them (mfu_est retained for
    comparison against the analytic estimate)."""

    def test_required_keys_cover_overlap(self, bench):
        required = set(bench.REQUIRED_METRIC_KEYS)
        assert "hvtpu_step_exposed_comm_seconds" in required
        assert "hvtpu_step_overlap_fraction" in required
        assert "hvtpu_mfu" in required

    def test_report_embeds_overlap_row(self, bench):
        report = bench.build_report(metric="m", value=1.0, unit="u")
        row = report["overlap"]
        assert set(row) == {"steps", "exposed_comm_seconds",
                            "overlap_fraction", "mfu"}
        assert row["steps"] == report["metrics"][
            "hvtpu_step_exposed_comm_seconds"]["count"]
        json.dumps(report)

    def test_unmeasured_gauges_report_null_not_zero(self, bench):
        from horovod_tpu.obs import stepprof

        stepprof.OVERLAP_FRACTION.set(0.0)
        stepprof.MFU.set(0.0)
        row = bench.build_report(metric="m", value=1.0,
                                 unit="u")["overlap"]
        # 0 means "never joined / no FLOPs provided", reported null so
        # a recorded 0.31 always means measured-0.31
        assert row["overlap_fraction"] is None
        assert row["mfu"] is None
        stepprof.OVERLAP_FRACTION.set(0.31)
        stepprof.MFU.set(0.42)
        try:
            row = bench.build_report(metric="m", value=1.0,
                                     unit="u")["overlap"]
            assert row["overlap_fraction"] == 0.31
            assert row["mfu"] == 0.42
        finally:
            stepprof.OVERLAP_FRACTION.set(0.0)
            stepprof.MFU.set(0.0)

    def test_recorded_rows_carry_measured_columns(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            data = json.load(f)
        assert data["results"]
        for row in data["results"]:
            assert "mfu_est" in row, row["model"]  # retained
            assert 0.0 < row["mfu_measured"] < 1.0, row["model"]
            # null until a device-profile round records it on hardware
            assert "overlap_fraction" in row, row["model"]
            assert row["exposed_comm_ms"] >= 0.0, row["model"]


class TestTorchStepSchema:
    """bench_eager's torch DistributedOptimizer step-time row: the
    schema is enforced so future rounds stay comparable, and
    BENCH_EAGER.json must actually carry a recorded P=4 row."""

    @pytest.fixture
    def bench_eager(self):
        import importlib

        import bench_eager as mod

        return importlib.reload(mod)

    def test_row_builder_schema(self, bench_eager):
        row = bench_eager.build_torch_step_row(4, 16, 1 << 20, 2.5)
        assert set(bench_eager.TORCH_STEP_KEYS) <= set(row)
        assert row["bench"] == "eager_torch_step"
        assert row["np"] == 4
        assert row["steps_per_s"] == pytest.approx(400.0)
        json.dumps(row)  # single JSON-serializable line

    def test_recorded_bench_has_torch_step_row(self, bench_eager):
        with open(os.path.join(_ROOT, "BENCH_EAGER.json")) as f:
            data = json.load(f)
        row = data["torch_step"]
        assert row["np"] == 4
        for key in bench_eager.TORCH_STEP_KEYS:
            assert key in row, key
        assert row["ms_per_step"] > 0


class TestPredictSchema:
    """Round 7: every controller-driven async row carries the
    schedule-prediction columns (predicted_fraction, mispredicts,
    mispredict_rate), and the recorded steady-state rows prove the
    default-on fast path actually engaged — predicted_fraction above
    0.8 with zero unrecovered mispredicts.  Round 8 adds
    zero_copy_fraction (fused ops riding the enqueue-time-packed
    exchange buffer) and requires it to be 1.0 on steady np=4 rows."""

    @pytest.fixture
    def bench_eager(self):
        import importlib

        import bench_eager as mod

        return importlib.reload(mod)

    def test_stats_builder_schema(self, bench_eager):
        before = {"cycles": 10, "predicted": 2, "mispredicts": 0,
                  "zero_copy": 4, "staged": 8}
        after = {"cycles": 74, "predicted": 58, "mispredicts": 1,
                 "zero_copy": 52, "staged": 24}
        stats = bench_eager.build_predict_stats(before, after)
        assert set(stats) == set(bench_eager.PREDICT_ROW_KEYS)
        assert stats["predicted_fraction"] == pytest.approx(56 / 64)
        assert stats["mispredicts"] == 1
        assert stats["mispredict_rate"] == pytest.approx(
            1 / 64, abs=1e-4)
        assert stats["zero_copy_fraction"] == pytest.approx(48 / 64)
        json.dumps(stats)

    def test_stats_builder_accepts_round7_snapshots(self, bench_eager):
        """Three-key snapshots (pre-round-8 recordings) still build:
        the fusion-path keys default to 0 -> null fraction."""
        before = {"cycles": 10, "predicted": 2, "mispredicts": 0}
        after = {"cycles": 74, "predicted": 58, "mispredicts": 1}
        stats = bench_eager.build_predict_stats(before, after)
        assert set(stats) == set(bench_eager.PREDICT_ROW_KEYS)
        assert stats["zero_copy_fraction"] is None

    def test_zero_cycle_window_is_null_not_crash(self, bench_eager):
        snap = {"cycles": 5, "predicted": 1, "mispredicts": 0,
                "zero_copy": 0, "staged": 0}
        stats = bench_eager.build_predict_stats(snap, dict(snap))
        assert stats["predicted_fraction"] is None
        assert stats["mispredict_rate"] is None
        assert stats["mispredicts"] == 0
        assert stats["zero_copy_fraction"] is None

    def test_recorded_steady_rows_predicted_without_mispredicts(
            self, bench_eager):
        with open(os.path.join(_ROOT, "BENCH_EAGER.json")) as f:
            data = json.load(f)
        async_np4 = [r for r in data["results"]
                     if r.get("np") == 4
                     and r["mode"].startswith("async")]
        assert async_np4
        for row in async_np4:
            for key in bench_eager.PREDICT_ROW_KEYS:
                assert key in row, (row["mode"], row["nbytes"], key)
            assert row["predicted_fraction"] > 0.8, row
            assert row["mispredicts"] == 0, row
            # round 8: the whole timed window rode the zero-copy path
            assert row["zero_copy_fraction"] == 1.0, row
        # the torch e2e step row rides the same schema
        for key in bench_eager.PREDICT_ROW_KEYS:
            assert key in data["torch_step"], key


class TestControlPlaneSimSchema:
    """BENCH_SCALING.json carries MEASURED control-plane rows from the
    fabric simulator (tools/hvtpusim bench): negotiation cycle,
    rendezvous, drain notice->commit vs world size.  These rows
    supersede the coordination_vs_P projection for control-plane
    scaling claims, so the schema is load-bearing: every row must be
    marked measured, cover the contracted world sizes, and carry
    finite positive virtual-time numbers."""

    REQUIRED_ROW_KEYS = {
        "ranks", "negotiation_cycle_p50_s", "negotiation_cycle_max_s",
        "rendezvous_s", "rendezvous_p50_s", "drain_notice_to_commit_s",
        "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["control_plane_sim"]
        assert "supersede" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["control_plane_sim"]["rows"]:
            for key in ("negotiation_cycle_p50_s",
                        "negotiation_cycle_max_s", "rendezvous_s",
                        "rendezvous_p50_s", "drain_notice_to_commit_s"):
                v = row[key]
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} {key}={v!r}")
            assert row["negotiation_cycle_p50_s"] <= (
                row["negotiation_cycle_max_s"])

    def test_projection_is_marked_superseded(self, doc):
        # the old extrapolation stays for history but must point at
        # the measured rows
        note = doc.get("coordination_note", "")
        assert "control_plane_sim" in note, (
            "coordination_vs_P must reference the measured "
            "control_plane_sim rows that supersede it")


class TestFleetArbiterSimSchema:
    """BENCH_SCALING.json carries MEASURED multi-job arbiter rows from
    the fabric simulator (tools/hvtpusim bench-fleet): gang queue wait,
    preemption notice->commit, and victim resize latency vs pool size.
    These back the docs/fleet.md latency claims, so the schema is
    load-bearing like the control-plane rows above."""

    REQUIRED_ROW_KEYS = {
        "ranks", "queue_wait_s", "preempt_notice_to_commit_s",
        "resize_s", "victims", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["fleet_arbiter_sim"]
        assert "drain" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["fleet_arbiter_sim"]["rows"]:
            for key in ("queue_wait_s", "preempt_notice_to_commit_s",
                        "resize_s"):
                v = row[key]
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} {key}={v!r}")
            # drain commit happens strictly inside the resize window
            assert row["preempt_notice_to_commit_s"] < row["resize_s"]
            # half the low-priority world is reclaimed for the arrival
            assert row["victims"] == row["ranks"] // 2


class TestCheckpointStormSimSchema:
    """BENCH_SCALING.json carries MEASURED durable-state-plane rows
    from the fabric simulator (tools/hvtpusim bench-ckpt): commit
    latency through the real commit protocol and restore-quorum
    latency at 64-1024 virtual ranks.  These back the
    docs/robustness.md durable-plane latency claims."""

    REQUIRED_ROW_KEYS = {
        "ranks", "commit_p50_s", "commit_p99_s", "quorum_p50_s",
        "quorum_max_s", "agreed_seq", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["checkpoint_storm_sim"]
        assert "measured" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["checkpoint_storm_sim"]["rows"]:
            for key in ("commit_p50_s", "commit_p99_s", "quorum_p50_s",
                        "quorum_max_s"):
                v = row[key]
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} {key}={v!r}")
            assert row["commit_p50_s"] <= row["commit_p99_s"]
            assert row["quorum_p50_s"] <= row["quorum_max_s"]
            # both storage victims fell back one commit: the agreed
            # restore point is commits-1 (the scenario default is 4)
            assert row["agreed_seq"] == 3


class TestAnomalyDetectionSimSchema:
    """BENCH_SCALING.json carries MEASURED straggler-detection-latency
    rows from the fabric simulator (tools/hvtpusim bench-anomaly): the
    real AnomalyEngine fed per-cycle arrival skew while one virtual
    rank's link degrades mid-run.  These back the
    docs/observability.md incident-detection claims."""

    REQUIRED_ROW_KEYS = {
        "ranks", "detection_latency_p50_s", "detection_latency_max_s",
        "seeds", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["anomaly_detection_sim"]
        assert "straggler" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_latencies_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["anomaly_detection_sim"]["rows"]:
            p50 = row["detection_latency_p50_s"]
            mx = row["detection_latency_max_s"]
            for v in (p50, mx):
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} latency={v!r}")
            assert p50 <= mx
            assert row["seeds"] >= 3

    def test_required_keys_cover_flight_and_incidents(self):
        import bench

        required = set(bench.REQUIRED_METRIC_KEYS)
        assert {"hvtpu_flight_events_total", "hvtpu_incidents_total",
                "hvtpu_fleet_job_step_rate",
                "hvtpu_fleet_job_incidents"} <= required


class TestCoordinatorLossSimSchema:
    """BENCH_SCALING.json carries MEASURED coordinator-loss recovery
    rows from the fabric simulator: coordinator death -> every
    survivor's lease-expiry self-fence (detect), then re-election +
    durable-key journal replay into the fresh KV (recover).  These
    back the docs/robustness.md coordination-plane claims."""

    REQUIRED_ROW_KEYS = {
        "ranks", "detect_p50_s", "detect_max_s", "fence_exits",
        "replayed_keys", "fence_to_recover_s", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["coordinator_loss_sim"]
        assert "journal" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["coordinator_loss_sim"]["rows"]:
            for key in ("detect_p50_s", "detect_max_s",
                        "fence_to_recover_s"):
                v = row[key]
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} {key}={v!r}")
            assert row["detect_p50_s"] <= row["detect_max_s"]
            # every rank fenced (split-brain window fully closed) and
            # every rank's journaled vote landed in the fresh KV
            assert row["fence_exits"] == row["ranks"]
            assert row["replayed_keys"] == row["ranks"]

    def test_required_keys_cover_fencing(self):
        import bench

        required = set(bench.REQUIRED_METRIC_KEYS)
        assert {"hvtpu_kv_fenced_writes_total",
                "hvtpu_fence_exits_total",
                "hvtpu_partition_suspect_seconds"} <= required


class TestPartitionStormSimSchema:
    """BENCH_SCALING.json carries MEASURED partition-storm rows from
    the fabric simulator: partition(MS) windows on three victims,
    peers classifying the silent ranks as partitioned-vs-dead by lease
    age, two thaw-and-recover, one lease-starved self-fence."""

    REQUIRED_ROW_KEYS = {
        "ranks", "detect_p50_s", "detect_max_s", "victims",
        "recovered", "fence_latency_s", "suspect_observations",
        "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["partition_storm_sim"]
        assert "suspect" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_positive_virtual_seconds(self, doc):
        for row in doc["partition_storm_sim"]["rows"]:
            for key in ("detect_p50_s", "detect_max_s",
                        "fence_latency_s"):
                v = row[key]
                assert isinstance(v, (int, float)) and 0 < v < 3600, (
                    f"ranks={row['ranks']} {key}={v!r}")
            assert row["detect_p50_s"] <= row["detect_max_s"]
            # exactly one victim fences; the thawed rest recover
            assert row["recovered"] == row["victims"] - 1
            assert row["suspect_observations"] > 0


class TestFleetServiceSimSchema:
    """BENCH_SCALING.json carries MEASURED fleet front-door rows from
    the fabric simulator (tools/hvtpusim bench-service): a seeded
    multi-tenant submission storm through the indexed journal into the
    real arbiter, with quotas, fair share, the starvation guard,
    torus placement, backpressure and an injected arbiter crash.
    These back the docs/fleet.md service-level claims, so the schema
    is load-bearing like the other sim families."""

    REQUIRED_ROW_KEYS = {
        "ranks", "jobs", "queue_wait_p50_s", "queue_wait_p99_s",
        "intake_p50_s", "intake_p99_s", "max_batch",
        "queue_full_rejections", "quota_rejections",
        "replayed_duplicates", "frag_mean", "preemptions",
        "aged_jobs", "starvation_gap_max_s", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["fleet_service_sim"]
        assert "exactly-once" in sim["note"].lower()
        rows = sim["rows"]
        # the tier-1 storm plus the 4096/16384 scale proofs
        assert {r["ranks"] for r in rows} >= {256, 4096, 16384}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_timings_are_finite_virtual_seconds(self, doc):
        for row in doc["fleet_service_sim"]["rows"]:
            # per-tier percentile maps: every tier present, finite,
            # p50 <= p99
            p50, p99 = row["queue_wait_p50_s"], row["queue_wait_p99_s"]
            assert set(p50) == set(p99) == {"0", "5", "10"}
            for tier in p50:
                assert 0 <= p50[tier] <= p99[tier] < 3600, (
                    f"ranks={row['ranks']} tier={tier}")
            assert 0 < row["intake_p50_s"] <= row["intake_p99_s"] < 3600
            assert 0 <= row["frag_mean"] <= 1
            assert 0 <= row["starvation_gap_max_s"] < 3600

    def test_front_door_invariants(self, doc):
        for row in doc["fleet_service_sim"]["rows"]:
            # the intake budget bound held at every pool size
            assert 0 < row["max_batch"] <= 256, row["ranks"]
            # backpressure, quota rejection and crash replay all
            # actually fired — rows from a storm that exercised
            # nothing would vacuously pass the timing checks
            assert row["queue_full_rejections"] >= 1
            assert row["quota_rejections"] >= 1
            assert row["replayed_duplicates"] >= 1
            assert row["jobs"] >= 2 * row["ranks"] // 8

    def test_required_keys_cover_front_door(self):
        import bench

        required = set(bench.REQUIRED_METRIC_KEYS)
        assert {"hvtpu_fleet_queue_depth", "hvtpu_fleet_intake_lag",
                "hvtpu_fleet_admission_rejections_total",
                "hvtpu_fleet_fragmentation"} <= required


class TestLossyLinkSimSchema:
    """BENCH_SCALING.json carries MEASURED lossy-link recovery rows
    from the fabric simulator (tools/hvtpusim bench-lossy): a seeded
    lossy fabric drops collective exchanges mid-step; the wire plane
    recovers them by consensus abort-and-retry plus ring route-around
    instead of restarting, and every row pairs the recovery cost with
    the restart-baseline cost of the SAME seed with retries disabled.
    These back the docs/robustness.md degradation-ladder claims."""

    REQUIRED_ROW_KEYS = {
        "ranks", "steps", "retry_rounds", "recovered_collectives",
        "consensus_p50_s", "consensus_max_s", "reroutes", "torn",
        "steps_lost_with_retries", "baseline_restarts",
        "baseline_steps_lost", "measured", "method",
    }

    @pytest.fixture
    def doc(self):
        with open(os.path.join(_ROOT, "BENCH_SCALING.json")) as f:
            return json.load(f)

    def test_measured_rows_present_and_complete(self, doc):
        sim = doc["lossy_link_sim"]
        assert "lossy" in sim["note"].lower()
        rows = sim["rows"]
        assert {r["ranks"] for r in rows} >= {64, 256, 1024}
        for row in rows:
            assert self.REQUIRED_ROW_KEYS <= set(row), row.get("ranks")
            assert row["measured"] is True
            assert "fabric-sim" in row["method"]

    def test_recovery_beats_restart_baseline(self, doc):
        for row in doc["lossy_link_sim"]["rows"]:
            # the lossy fabric actually bit, and retries absorbed it:
            # no torn results, no steps lost — while the SAME seed
            # with retries disabled restarted and lost work
            assert row["retry_rounds"] >= 1, row["ranks"]
            assert row["recovered_collectives"] >= 1, row["ranks"]
            assert row["torn"] == 0, row["ranks"]
            assert row["steps_lost_with_retries"] == 0, row["ranks"]
            assert row["baseline_restarts"] >= 1, row["ranks"]
            assert row["baseline_steps_lost"] > 0, row["ranks"]
            v = row["consensus_p50_s"]
            assert isinstance(v, (int, float)) and 0 < v < 3600, (
                f"ranks={row['ranks']} consensus_p50_s={v!r}")
            assert row["consensus_p50_s"] <= row["consensus_max_s"]

    def test_required_keys_cover_wire_plane(self):
        import bench

        required = set(bench.REQUIRED_METRIC_KEYS)
        assert {"hvtpu_collective_retries_total",
                "hvtpu_collective_abort_consensus_seconds",
                "hvtpu_link_health",
                "hvtpu_ring_reroutes_total"} <= required
