"""Scaling-efficiency harness: throughput vs device count, the
measurement behind the reference's headline '~90% scaling efficiency'
claims (README.rst Benchmarks / docs/benchmarks.rst methodology:
synthetic data, images/sec at N workers over images/sec at 1 worker
times N).

Sweeps a DP training step over 1..N devices of one mesh and prints one
JSON line per point:

  {"bench": "scaling", "devices": d, "img_per_sec": ...,
   "efficiency_vs_linear": ...}

Default run uses the 8-device virtual CPU mesh (mechanics; this sandbox
has a single real TPU chip — on a pod, run unmodified for real ICI
numbers).  --platform tpu keeps whatever devices the default backend
exposes.
"""

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for --platform cpu")
    p.add_argument("--batch-per-device", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--model", default="mlp", choices=["mlp", "resnet18"])
    args = p.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvt

    hvt.init()
    all_devs = jax.devices()

    if args.model == "mlp":
        from horovod_tpu.models.mlp import MLP

        model = MLP(features=(1024, 1024, 256), num_classes=100)
        x_shape = (784,)
    else:
        from horovod_tpu.models import ResNet18

        model = ResNet18(num_classes=100, dtype=jnp.bfloat16)
        x_shape = (64, 64, 3)

    rng = jax.random.PRNGKey(0)

    def throughput(devs):
        d = len(devs)
        mesh = Mesh(np.array(devs), ("dp",))
        gb = args.batch_per_device * d
        x = jax.random.normal(rng, (gb,) + x_shape,
                              jnp.bfloat16 if args.model == "resnet18"
                              else jnp.float32)
        y = jax.random.randint(rng, (gb,), 0, 100)
        variables = model.init(rng, x[:2]) if args.model == "mlp" else \
            model.init(rng, x[:2], train=True)

        tx = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name="dp")
        params = variables["params"]
        extra = {k: v for k, v in variables.items() if k != "params"}
        opt_state = tx.init(params)

        def loss_fn(params, x, y):
            if extra:
                logits, _ = model.apply(
                    {"params": params, **extra}, x, train=True,
                    mutable=list(extra),
                )
            else:
                logits = model.apply({"params": params}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        def body(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    jax.lax.pmean(loss, "dp"))

        step = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(), P("dp"), P("dp")),
                out_specs=(P(), P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        dt = time.perf_counter() - t0
        return gb * args.iters / dt

    results = []
    base = None
    d = 1
    while d <= len(all_devs):
        ips = throughput(all_devs[:d])
        if base is None:
            base = ips
        eff = ips / (base * d)
        results.append({
            "bench": "scaling", "model": args.model, "devices": d,
            "img_per_sec": round(ips, 1),
            "efficiency_vs_linear": round(eff, 4),
        })
        d *= 2
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
