"""Cross-rank distributed tracing (obs/tracing.py + tools/hvtputrace).

Acceptance shape (ISSUE PR 7): a 2-process CPU job with
``HVTPU_TRACE`` set and a 50 ms pre-collective fault on rank 1 must
yield per-rank traces that ``hvtputrace merge`` fuses into one valid
Chrome-trace JSON with correlated spans for the same collective on
both ranks plus a recorded clock offset, and ``hvtputrace report``
must attribute the straggling to rank 1.  With ``HVTPU_TRACE`` unset
the hot-path guard must be a single module-attribute check (same
contract as core/faults.ACTIVE).
"""

import json
import os
import time

import pytest

import horovod_tpu
from horovod_tpu.obs import tracing
from horovod_tpu.runner import run
from tools import hvtputrace

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


def _events(path):
    with open(path) as f:
        return json.load(f)


# --------------------------------------------------------------------------
# tracer unit tests
# --------------------------------------------------------------------------

class TestTracer:
    def test_trace_ids_are_rank_agnostic_occurrence_counts(self, tmp_path):
        tr = tracing.Tracer(str(tmp_path), rank=0, size=1)
        tr.op_begin("g", "allreduce")
        tr.op_phase("g", tracing.QUEUE)
        tr.op_phase("g", tracing.EXEC)
        tr.op_done("g", bytes=64)
        tr.op_begin("g", "allreduce")  # second occurrence: g#1
        tr.op_done("g")
        tr.close()
        evs = _events(tmp_path / "rank0.trace.json")
        ids = [e["args"]["trace_id"] for e in evs
               if e.get("ph") in ("B", "i")
               and "trace_id" in e.get("args", {})]
        assert ids == ["g#0", "g#0", "g#0", "g#0", "g#1", "g#1"]
        # DONE instant carries the result metadata
        done = [e for e in evs if e.get("name") == "DONE"]
        assert done[0]["args"]["bytes"] == 64

    def test_phase_and_done_ignore_untracked_names(self, tmp_path):
        """Responses for process sets this rank is not a member of
        arrive with names that never began a span here: no-ops."""
        tr = tracing.Tracer(str(tmp_path), rank=0, size=1)
        tr.op_phase("ghost", tracing.EXEC)
        tr.op_done("ghost")
        tr.close()
        evs = _events(tmp_path / "rank0.trace.json")
        assert not any(e.get("ph") in ("B", "E") and e.get("cat") == "tensor"
                       for e in evs)

    def test_anchor_written_first_survives_truncation(self, tmp_path):
        tr = tracing.Tracer(str(tmp_path), rank=0, size=2)
        tr.op_begin("g", "allreduce")
        # simulate a crash: never op_done / close — file has no closing
        # bracket and a dangling B event
        tr._tl._file.flush()
        evs = hvtputrace._load_events(str(tmp_path / "rank0.trace.json"))
        wall_t0_us, _off, _err = hvtputrace.clock_metadata(evs)
        assert wall_t0_us is not None
        tr.close()

    def test_install_uninstall_flip_active_flag(self, tmp_path):
        assert tracing.ACTIVE is False
        try:
            tr = tracing.install(str(tmp_path), rank=0, size=1)
            assert tracing.ACTIVE is True
            assert tracing.get_tracer() is tr
            tracing.op_begin("x", "allreduce")
            tracing.op_done("x")
        finally:
            tracing.uninstall()
            tracing.uninstall()  # idempotent
        assert tracing.ACTIVE is False and tracing.get_tracer() is None
        evs = _events(tmp_path / "rank0.trace.json")
        assert any(e.get("name") == "DONE" for e in evs)

    def test_clock_sync_over_kv(self, tmp_path):
        """Same-process FakeKV handshake: the peer's min-RTT offset is
        near zero with a positive error bound, and both facts land in
        the trace metadata."""
        from test_eager_controller import FakeKV

        kv = FakeKV()
        t0 = tracing.Tracer(str(tmp_path), rank=0, size=2)
        t1 = tracing.Tracer(str(tmp_path), rank=1, size=2)
        t0.sync_clock(kv, pings=4)   # spawns the responder daemon
        t1.sync_clock(kv, pings=4)
        assert t1.offset_us is not None
        assert abs(t1.offset_us) < 1e6      # same host: well under 1 s
        assert t1.offset_error_us > 0
        t0.close()
        t1.close()
        _w, off, err = hvtputrace.clock_metadata(
            _events(tmp_path / "rank1.trace.json"))
        assert off == t1.offset_us and err == t1.offset_error_us

    def test_clock_sync_degrades_without_client(self, tmp_path):
        tr = tracing.Tracer(str(tmp_path), rank=1, size=2)
        tr.sync_clock(None, pings=4)
        assert tr.offset_us is None  # merge falls back to offset 0
        tr.close()


# --------------------------------------------------------------------------
# merge / report over synthetic two-rank traces
# --------------------------------------------------------------------------

class TestMergeReport:
    @pytest.fixture
    def skewed_dir(self, tmp_path):
        """Two same-process tracers; rank 1 begins each collective
        ~40 ms late (deterministic straggler, shared wall clock)."""
        t0 = tracing.Tracer(str(tmp_path), rank=0, size=2)
        t1 = tracing.Tracer(str(tmp_path), rank=1, size=2)
        for _ in range(2):
            t0.op_begin("g", "allreduce")
            t0.op_done("g", bytes=64)
            time.sleep(0.04)
            t1.op_begin("g", "allreduce")
            t1.op_done("g", bytes=64)
        t0.close()
        t1.close()
        return tmp_path

    def test_merge_rebases_onto_one_clock(self, skewed_dir):
        merged = hvtputrace.merge(str(skewed_dir))
        json.dumps(merged)  # Perfetto-loadable event array
        assert {e.get("pid") for e in merged if e.get("ph") == "B"} \
            == {0, 1}
        # the same trace_id appears on both process lanes
        by_rank = {r: {e["args"]["trace_id"] for e in merged
                       if e.get("ph") == "B" and e.get("pid") == r}
                   for r in (0, 1)}
        assert by_rank[0] & by_rank[1] == {"g#0", "g#1"}

    def test_report_attributes_straggler(self, skewed_dir):
        rep = hvtputrace.report(str(skewed_dir))
        assert rep["ranks"] == [0, 1]
        assert len(rep["collectives"]) == 2
        for c in rep["collectives"]:
            assert c["last_rank"] == 1
            assert c["arrival_skew_us"] > 20_000
        assert rep["stragglers"][0]["rank"] == 1
        assert rep["stragglers"][0]["times_last"] == 2
        for r in (0, 1):
            row = rep["per_rank"][r]
            assert row["wait_us"] >= 0
            assert row["trace_extent_us"] >= row["wait_us"]
        # render path stays exception-free and names the straggler
        assert "rank 1" in hvtputrace.render_report(rep)

    def test_cli_merge_and_report(self, skewed_dir, capsys):
        from tools.hvtputrace.__main__ import main

        assert main(["merge", str(skewed_dir)]) == 0
        out = skewed_dir / "merged.trace.json"
        assert {e.get("pid") for e in _events(out)} == {0, 1}
        capsys.readouterr()  # drain the merge status line
        assert main(["report", str(skewed_dir), "--json"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["stragglers"][0]["rank"] == 1

    def test_truncated_rank_file_tolerated(self, skewed_dir):
        path = skewed_dir / "rank1.trace.json"
        text = path.read_text()
        path.write_text(text[: int(len(text) * 0.8)])
        rep = hvtputrace.report(str(skewed_dir))
        assert 1 in rep["per_rank"]

    def test_empty_dir_names_the_knob(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="HVTPU_TRACE"):
            hvtputrace.load_rank_traces(str(tmp_path))


# --------------------------------------------------------------------------
# lifecycle: init/shutdown wiring, timeline swap, flush on exit
# --------------------------------------------------------------------------

class TestLifecycle:
    def test_shutdown_flushes_trace(self, tmp_path, monkeypatch):
        """HVTPU_TRACE at init() installs the tracer; shutdown() (also
        the atexit hook's path) flushes a strictly-valid JSON file."""
        import jax.numpy as jnp

        monkeypatch.setenv("HVTPU_TRACE", str(tmp_path))
        horovod_tpu.init()
        try:
            assert tracing.ACTIVE is True
            horovod_tpu.allreduce(jnp.ones((16,), jnp.float32))
            h = horovod_tpu.allreduce_async(jnp.ones((8,), jnp.float32))
            horovod_tpu.synchronize(h)
        finally:
            horovod_tpu.shutdown()
        assert tracing.ACTIVE is False
        # strict parse: close() wrote the bracket, no repair needed
        evs = _events(tmp_path / "rank0.trace.json")
        assert any(e.get("name") == "DONE" for e in evs)
        # single-rank report still works (no multi-rank collectives)
        rep = hvtputrace.report(str(tmp_path))
        assert rep["stragglers"] == []

    def test_timeline_swap_under_live_controller(self, hvt, tmp_path):
        """start_timeline/stop_timeline while a live eager controller
        holds `_timeline`: the rebind must reach the controller and
        both files must stay parseable."""
        import jax.numpy as jnp

        from horovod_tpu.core import state as core_state

        f1, f2 = str(tmp_path / "t1.json"), str(tmp_path / "t2.json")
        hvt.start_timeline(f1)
        h = hvt.allreduce_async(jnp.ones((8,), jnp.float32))
        hvt.synchronize(h)
        st = core_state._state
        assert st.controller is not None
        tl2 = hvt.start_timeline(f2)  # swap under the live controller
        assert st.controller._timeline is tl2
        h = hvt.allreduce_async(jnp.ones((8,), jnp.float32))
        hvt.synchronize(h)
        hvt.stop_timeline()
        assert st.controller._timeline is None
        # one more op after stop: no timeline, no crash
        h = hvt.allreduce_async(jnp.ones((8,), jnp.float32))
        hvt.synchronize(h)
        for f in (f1, f2):
            assert isinstance(_events(f), list)
        # the second file captured the post-swap op
        assert any(e.get("cat") == "tensor" for e in _events(f2))


# --------------------------------------------------------------------------
# disabled path: one attribute check (mirrors test_faults' guard)
# --------------------------------------------------------------------------

def test_inactive_guard_is_zero_overhead():
    """Acceptance: with HVTPU_TRACE unset the hot-path hook is one
    module-attribute read — far under a microsecond per op, so traced
    builds cost nothing when tracing is off."""
    import timeit

    assert tracing.ACTIVE is False
    n = 100_000
    t = timeit.timeit(
        lambda: tracing.ACTIVE and tracing.op_begin("x", "allreduce"),
        number=n)
    assert t / n < 5e-6, f"{t / n * 1e9:.0f} ns/op"


# --------------------------------------------------------------------------
# 2-process acceptance: fault-skewed job -> merged trace + attribution
# --------------------------------------------------------------------------

@pytest.mark.multiprocess
def test_trace_acceptance_2proc(tmp_path):
    """End to end: rank 1 suffers a 50 ms pre-collective delay; the
    merged trace correlates both ranks' spans per collective, records
    the KV clock offset, the report blames rank 1, and /debug answers
    live controller state while the job runs."""

    trace_dir = str(tmp_path)

    def body():
        import json as _json
        import urllib.request

        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.obs import tracing as _tracing

        hvt.init()
        assert _tracing.ACTIVE is True
        r = hvt.rank()
        for _ in range(3):
            hvt.allreduce(jnp.ones((1024,), jnp.float32))
        h = hvt.allreduce_async(jnp.full((8,), float(r)))
        hvt.synchronize(h)
        # live /debug probe while the controller is up
        port = 19750 + hvt.local_rank()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug", timeout=30) as resp:
            assert resp.status == 200
            dbg = _json.loads(resp.read().decode())
        ctrl = dbg["controller"]
        assert ctrl["size"] == 2 and ctrl["queue_depth"] >= 0
        assert "capacity" in ctrl["cache"]
        assert dbg["job"]["initialized"] is True
        assert "mode" in dbg["stall"]
        if dbg["stall"]["mode"] == "amortized":
            assert "peer_heartbeat_age_s" in dbg["stall"]
        hvt.shutdown()
        return "ok"

    env = dict(
        _ENV,
        HVTPU_TRACE=trace_dir,
        HVTPU_METRICS_PORT="19750",
        HVTPU_FAULT_SPEC="collective.pre:delay(50)@rank=1",
    )
    assert run(body, np=2, cpu_devices=1, env=env,
               start_timeout=300.0) == ["ok", "ok"]

    # one valid Chrome-trace JSON with a lane per rank
    from tools.hvtputrace.__main__ import main

    assert main(["merge", trace_dir]) == 0
    merged = _events(tmp_path / "merged.trace.json")
    assert {e.get("pid") for e in merged if e.get("ph") == "B"} == {0, 1}

    # correlated spans: the same collective's trace_id on both lanes
    ids = {r: {e["args"]["trace_id"] for e in merged
               if e.get("ph") == "B" and e.get("pid") == r
               and "trace_id" in e.get("args", {})}
           for r in (0, 1)}
    assert ids[0] & ids[1], "no cross-rank correlated collectives"

    # rank 1 recorded a KV clock offset with its error bound
    traces = hvtputrace.load_rank_traces(trace_dir)
    _w, off1, err1 = hvtputrace.clock_metadata(traces[1])
    assert off1 is not None and err1 is not None and err1 > 0

    # attribution: the injected 50 ms delay makes rank 1 the straggler
    rep = hvtputrace.report(trace_dir)
    assert rep["stragglers"], "report found no stragglers"
    top = rep["stragglers"][0]
    assert top["rank"] == 1
    assert top["total_skew_us"] > 10_000
