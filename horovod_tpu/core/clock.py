"""Injectable clock seam for every control-plane timing decision.

The control plane reads time in four ways — ``monotonic()`` for
durations and deadlines, ``wall()`` for human-facing timestamps,
``sleep()`` for backoff/poll loops, and ``call_later()`` for one-shot
timers (preempt grace).  Production code must route all four through
this module instead of calling :mod:`time` / :class:`threading.Timer`
directly, so the fabric simulator (horovod_tpu/sim) can substitute a
virtual clock per rank thread and advance time discretely with no real
sleeps.

Installation is **thread-local**: the simulator installs a virtual
clock on each virtual-rank thread only; unregistered threads (pytest's
main thread, real production workers) fall through to the process-wide
default, which is the real :class:`SystemClock` unless overridden with
:func:`set_default`.  That split is what lets one process host 4096
virtual ranks on virtual time while the hosting test itself still sees
real time.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class Clock:
    """Interface: the four timing primitives the control plane uses."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def wall(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> "Timer":
        raise NotImplementedError


class Timer:
    """Handle returned by :meth:`Clock.call_later`; ``cancel()`` is
    best-effort (the callback may already be running)."""

    def cancel(self) -> None:  # pragma: no cover - interface default
        pass


class _ThreadingTimer(Timer):
    def __init__(self, t: threading.Timer):
        self._t = t

    def cancel(self) -> None:
        self._t.cancel()


class SystemClock(Clock):
    """The real thing: time.monotonic / time.time / time.sleep /
    threading.Timer."""

    def monotonic(self) -> float:
        return time.monotonic()

    def wall(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> Timer:
        t = threading.Timer(max(0.0, delay_s), fn)
        t.daemon = True
        t.start()
        return _ThreadingTimer(t)


_SYSTEM = SystemClock()
_default: Clock = _SYSTEM
_tls = threading.local()


def get() -> Clock:
    """The clock for the *calling thread*: its thread-local override if
    one is installed, else the process default."""
    c = getattr(_tls, "clock", None)
    return c if c is not None else _default


def install(clock: Optional[Clock]) -> None:
    """Install ``clock`` as this thread's clock (None to uninstall)."""
    _tls.clock = clock


def installed() -> Optional[Clock]:
    """This thread's override, or None when running on the default."""
    return getattr(_tls, "clock", None)


def set_default(clock: Optional[Clock]) -> None:
    """Replace the process-wide default (None restores SystemClock).
    Tests only; production leaves the SystemClock in place."""
    global _default
    _default = clock if clock is not None else _SYSTEM


# Convenience free functions — call sites read as ``clock.monotonic()``
# which keeps diffs against the old ``time.monotonic()`` spelling small.

def monotonic() -> float:
    return get().monotonic()


def wall() -> float:
    return get().wall()


def sleep(seconds: float) -> None:
    get().sleep(seconds)


def call_later(delay_s: float, fn: Callable[[], None]) -> Timer:
    return get().call_later(delay_s, fn)
