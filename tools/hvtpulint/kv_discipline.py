"""kv-discipline pass: no raw coordination-client KV traffic.

Every KV operation against the jax coordination service must go
through the ``core/retry.py`` wrappers — ``resilient_kv`` (retry with
backoff, metrics) or ``fenced_kv`` (generation fencing, liveness
lease, durable-key journal).  A raw ``key_value_*`` call on the bare
client bypasses all three coordination-plane fault-tolerance layers:
a superseded zombie can publish stale state, transient coordinator
blips surface as instant failures, and durable writes are invisible
to the coordinator-loss replay journal (docs/robustness.md,
"Coordination-plane fault tolerance").

The pass tracks, per function scope, names bound from the raw client
singleton (``…global_state.client``) and flags:

  * a ``key_value_*`` / ``blocking_key_value_*`` call on such a name
    (or directly on the ``global_state.client`` chain) — the classic
    raw get/put;
  * storing a raw name on ``self`` (``self._kv = client``) — the
    client escapes into instance state unwrapped, so every later call
    through that attribute is raw.  The escape is flagged once, at
    the assignment, rather than at each downstream call site.

A raw name is discharged when it is passed to ``fenced_kv``/
``resilient_kv`` (including the common rebind
``client = fenced_kv(client, …)``) or re-assigned any non-raw value.
Legitimate bootstrap-before-init paths that truly need the bare
client carry a justified entry in ``.hvtpulint.suppress``.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from . import Finding, Project

PASS = "kv-discipline"

SCAN_DIR = "horovod_tpu"

#: factory names that wrap a raw client (core/retry.py); passing a raw
#: name into one of these discharges it.
WRAPPERS = {"fenced_kv", "resilient_kv"}


def _is_raw_chain(node: ast.AST) -> bool:
    """``<anything>.global_state.client`` attribute chain."""
    return (isinstance(node, ast.Attribute) and node.attr == "client"
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "global_state")


def _is_kv_method(attr: str) -> bool:
    return attr.startswith(("key_value_", "blocking_key_value_"))


def _call_name(fn: ast.AST) -> str:
    """Terminal name of a call target: ``fenced_kv`` for both
    ``fenced_kv(...)`` and ``core_retry.fenced_kv(...)``."""
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class _Visitor(ast.NodeVisitor):
    def __init__(self):
        # names currently bound to the raw client in this scope
        self.raw: Dict[str, int] = {}
        self.hits: List[tuple] = []  # (line, canonical)

    # -- scoping: raw bindings don't leak across function boundaries --
    def _scoped(self, node: ast.AST) -> None:
        saved, self.raw = self.raw, {}
        self.generic_visit(node)
        self.raw = saved

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scoped(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scoped(node)

    # -- bindings ------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        raw_value = (_is_raw_chain(node.value)
                     or (isinstance(node.value, ast.Name)
                         and node.value.id in self.raw))
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if raw_value:
                    self.raw[tgt.id] = node.lineno
                else:
                    self.raw.pop(tgt.id, None)
            elif (isinstance(tgt, ast.Attribute) and raw_value
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == "self"):
                self.hits.append((node.lineno, f"escape:{tgt.attr}"))
        self.generic_visit(node)

    # -- uses ----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if _call_name(fn) in WRAPPERS:
            # client handed to a core/retry wrapper: discharged
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.raw.pop(arg.id, None)
        elif isinstance(fn, ast.Attribute) and _is_kv_method(fn.attr):
            base = fn.value
            if ((isinstance(base, ast.Name) and base.id in self.raw)
                    or _is_raw_chain(base)):
                self.hits.append((node.lineno, f"call:{fn.attr}"))
        self.generic_visit(node)


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for path in project.py_files(SCAN_DIR):
        tree = project.parse(path)
        if tree is None:
            continue
        visitor = _Visitor()
        visitor.visit(tree)
        rel = project.rel(path)
        counts: Dict[str, int] = {}
        for line, canonical in visitor.hits:
            n = counts[canonical] = counts.get(canonical, 0) + 1
            if canonical.startswith("escape:"):
                msg = ("raw coordination client stored on "
                       f"self.{canonical.split(':', 1)[1]} without a "
                       "FencedKV/ResilientKV wrapper — every KV call "
                       "through it skips fencing, retry, and the "
                       "durable-key journal (core/retry.py)")
            else:
                msg = (f"raw coordination-client {canonical.split(':', 1)[1]}"
                       "() outside FencedKV/ResilientKV — wrap the client "
                       "with core.retry.fenced_kv/resilient_kv so fencing, "
                       "retry, and journaling apply")
            findings.append(Finding(
                PASS, rel, line, f"{canonical}:{path.name}:{n}", msg))
    return findings
