"""metrics-catalog fixture (clean): registry, docs, and bench agree."""

from .registry import counter, gauge

STEPS = counter("hvtpu_fixture_steps_total", "Completed steps.")
DEPTH = gauge("hvtpu_fixture_queue_depth", "Pending items.")
