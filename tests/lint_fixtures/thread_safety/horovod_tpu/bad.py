"""thread-safety fixture: lock-discipline violations the pass must flag."""

import threading


class BadWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []          # hvtpulint: guarded-by(_lock)
        self._depth = 0           # hvtpulint: guarded-by(_lock, racy-read-ok)
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        while True:
            self._drain()

    def _drain(self):             # hvtpulint: requires(_lock)
        while self._queue:
            self._queue.pop()

    def submit(self, item):
        # Bad: unlocked write to a guarded attribute.
        self._queue.append(item)
        # Bad: calling a requires(_lock) method without the lock.
        self._drain()

    def bump(self):
        # racy-read-ok permits the read but this is a *write*.
        self._depth = self._depth + 1

    def peek_depth(self):
        # Fine: racy-read-ok read.
        return self._depth
