"""Online autotuning of fusion threshold / cycle time.

Parity surface: ``horovod/common/parameter_manager.cc``
(``ParameterManager``) + ``horovod/common/optim/bayesian_optimization.cc``
— enabled by ``HVTPU_AUTOTUNE=1``, scoring each sampled configuration by
observed throughput and converging on the best, optionally logging every
sample to ``HVTPU_AUTOTUNE_LOG`` as CSV.

Two search strategies:

* ``gp`` (default, reference parity): a Gaussian process with Expected
  Improvement over (log2 fusion threshold, cycle time), seeded with the
  reference's default operating points, sampling
  ``autotune_gp_samples`` configurations before pinning the best.
* ``grid``: successive sweep of a discrete log-grid (cheap-and-robust
  fallback; also what the tests drive deterministically).

Each candidate gets ``autotune_steps_per_sample`` steps; scores are
bytes/sec moved by the eager controller.
"""

from __future__ import annotations

import csv
import time
from typing import List, Optional, Tuple

# (fusion_threshold_bytes, cycle_time_ms) candidates — log grid around
# the reference defaults (64 MB, 1-5 ms).
_DEFAULT_GRID: List[Tuple[int, float]] = [
    (2 * 1024 * 1024, 1.0),
    (8 * 1024 * 1024, 1.0),
    (32 * 1024 * 1024, 1.0),
    (64 * 1024 * 1024, 1.0),
    (64 * 1024 * 1024, 2.5),
    (128 * 1024 * 1024, 2.5),
    (128 * 1024 * 1024, 5.0),
]

# GP search box: log2(bytes) in [2 MB, 256 MB], cycle time 0.5-10 ms
_GP_BOUNDS = [(21.0, 28.0), (0.5, 10.0)]
# seed points (log2 threshold, cycle ms): the reference defaults
_GP_SEEDS = [(26.0, 1.0), (21.0, 1.0), (27.0, 5.0)]


class Autotuner:
    def __init__(self, config, grid: Optional[List[Tuple[int, float]]] = None,
                 mode: Optional[str] = None):
        self._steps_per_sample = max(1, config.autotune_steps_per_sample)
        self._warmup = max(0, config.autotune_warmup_samples)
        self._log_path = config.autotune_log
        # an explicit grid ALWAYS means grid mode (callers/tests chose
        # their candidates); otherwise the config decides
        if grid is not None:
            self.mode = "grid"
        else:
            self.mode = (mode
                         or getattr(config, "autotune_mode", None)
                         or "gp")
        self._grid = list(grid or _DEFAULT_GRID)
        self._max_gp_samples = getattr(config, "autotune_gp_samples", 12)
        if self.mode == "gp":
            from .gaussian_process import BayesianOptimizer

            self._bo = BayesianOptimizer(_GP_BOUNDS, seed_points=_GP_SEEDS)
            self._active = self._point_to_params(self._bo.suggest())
        else:
            self._bo = None
            self._active = self._grid[0]
        self._candidate = 0
        self._scores: List[float] = []
        # raw params per GP observation: pinning must return the EXACT
        # candidate that was run, not a log2/2** float round-trip of it
        # (the round-trip can shift the integer threshold by 1 ulp,
        # yielding a "best" config that was never actually sampled)
        self._gp_observed: List[Tuple[int, float]] = []
        self._steps = 0
        self._bytes = 0
        self._t_start = time.monotonic()
        self._pinned: Optional[Tuple[int, float]] = None
        self._warmup_left = self._warmup
        if self._log_path:
            with open(self._log_path, "w", newline="") as f:
                csv.writer(f).writerow(
                    ["fusion_threshold", "cycle_time_ms", "bytes_per_sec"]
                )

    @staticmethod
    def _point_to_params(pt) -> Tuple[int, float]:
        log2_thr, cyc = float(pt[0]), float(pt[1])
        return int(2.0 ** log2_thr), round(cyc, 3)

    @staticmethod
    def _params_to_point(params):
        import math

        thr, cyc = params
        return (math.log2(max(thr, 1)), cyc)

    @property
    def current(self) -> Tuple[int, float]:
        """Active (fusion_threshold_bytes, cycle_time_ms)."""
        if self._pinned is not None:
            return self._pinned
        return self._active

    @property
    def done(self) -> bool:
        return self._pinned is not None

    def _log_sample(self, score: float):
        if self._log_path:
            thr, cyc = self._active
            with open(self._log_path, "a", newline="") as f:
                csv.writer(f).writerow([thr, cyc, f"{score:.1f}"])

    def record_step(self, nbytes: int):
        """Report one training/communication step of ``nbytes`` reduced.

        Drives the sampling schedule; call once per step from the eager
        controller cycle (or a training loop).
        """
        if self._pinned is not None:
            return
        if self._warmup_left > 0:
            self._warmup_left -= 1
            self._t_start = time.monotonic()
            return
        self._steps += 1
        self._bytes += nbytes
        if self._steps < self._steps_per_sample:
            return
        elapsed = max(time.monotonic() - self._t_start, 1e-9)
        score = self._bytes / elapsed
        self._log_sample(score)
        self._steps = 0
        self._bytes = 0
        if self.mode == "gp":
            self._bo.observe(self._params_to_point(self._active), score)
            self._gp_observed.append(self._active)
            if self._bo.num_observations >= self._max_gp_samples:
                self._pinned = self._gp_observed[self._bo.best_index]
            else:
                self._active = self._point_to_params(self._bo.suggest())
        else:
            self._scores.append(score)
            self._candidate += 1
            if self._candidate >= len(self._grid):
                best = max(range(len(self._scores)),
                           key=self._scores.__getitem__)
                self._pinned = self._grid[best]
            else:
                self._active = self._grid[self._candidate]
        self._t_start = time.monotonic()
