"""TF/Keras elastic state (parity: ``horovod/tensorflow/elastic.py``
``TensorFlowKerasState``): capture model + optimizer weights for
commit/rollback and broadcast them on sync."""

from __future__ import annotations

import copy
from typing import Any, Dict

import numpy as np

from ..elastic import run  # noqa: F401  (parity: hvd.elastic.run)
from ..elastic.state import ObjectState


class TensorFlowState(ObjectState):
    """Elastic state over a plain list of ``tf.Variable``s (parity:
    ``horovod/tensorflow/elastic.py`` ``TensorFlowState(variables,
    session)``).  TF2-idiomatic: eager variables, no session — pass the
    variables explicitly (the reference's no-arg default reads the TF1
    global-variables collection, which does not exist eagerly)."""

    def __init__(self, variables=None, **kwargs):
        if variables is None:
            # The reference's no-arg default reads the TF1
            # global-variables collection under a session; this build
            # is TF2-eager only, where graph RefVariables would not
            # survive _capture's .numpy() anyway — require the list.
            raise ValueError(
                "TensorFlowState needs an explicit `variables` list "
                "(TF2 eager has no global-variables collection); pass "
                "e.g. model.trainable_variables")
        self._variables = list(variables)
        super().__init__(**kwargs)  # ObjectState snapshots at the end

    def _capture(self) -> Dict[str, Any]:
        payload = super()._capture()
        payload["__vars__"] = [np.asarray(v.numpy())
                               for v in self._variables]
        return payload

    def _apply(self, payload: Dict[str, Any]):
        for k, v in payload.items():
            if k == "__vars__":
                if len(v) != len(self._variables):
                    raise ValueError(
                        f"snapshot holds {len(v)} variables but this "
                        f"state tracks {len(self._variables)} — the "
                        "variable list changed since the commit; "
                        "refusing a partial restore")
                for var, val in zip(self._variables, v):
                    var.assign(val)
            else:
                setattr(self, k, v)


class TensorFlowKerasState(ObjectState):
    """Elastic state for a keras model (+ optional optimizer) plus
    plain attributes (parity: TensorFlowKerasState(model, optimizer,
    batch=0, epoch=0))."""

    def __init__(self, model, optimizer=None, **kwargs):
        self._model_handle = model
        self._opt_handle = optimizer
        super().__init__(**kwargs)
        self.model = model
        self.optimizer = optimizer
        self.save_to_memory()

    def _capture(self) -> Dict[str, Any]:
        payload = {
            k: copy.deepcopy(getattr(self, k)) for k in self._tracked
        }
        payload["__model_weights__"] = [
            np.asarray(w) for w in self._model_handle.get_weights()
        ]
        if self._opt_handle is not None:
            opt_vars = self._opt_handle.variables
            if callable(opt_vars):  # legacy optimizers: method not prop
                opt_vars = opt_vars()
            payload["__opt_vars__"] = [np.asarray(v) for v in opt_vars]
        return payload

    def _opt_vars(self):
        opt_vars = self._opt_handle.variables
        if callable(opt_vars):  # legacy optimizers: method not prop
            opt_vars = opt_vars()
        return opt_vars

    def _apply(self, payload: Dict[str, Any]):
        for k, v in payload.items():
            if k == "__model_weights__":
                self._model_handle.set_weights(list(v))
            elif k == "__opt_vars__":
                opt_vars = self._opt_vars()
                if len(opt_vars) != len(v) \
                        and not getattr(self._opt_handle, "built", True):
                    # Elastic restart: the relaunched process holds a
                    # FRESH optimizer whose slot variables (momentum
                    # etc.) don't exist until build — a plain zip
                    # would silently drop the committed slots.  Build
                    # against the model's trainables, then restore.
                    try:
                        self._opt_handle.build(
                            self._model_handle.trainable_variables)
                    except Exception:
                        pass
                    opt_vars = self._opt_vars()
                if len(opt_vars) != len(v):
                    raise ValueError(
                        f"snapshot holds {len(v)} optimizer variables "
                        f"but the live optimizer has {len(opt_vars)} "
                        "— commit after the optimizer's first step, "
                        "or pass a built optimizer; refusing a "
                        "partial restore")
                for var, val in zip(opt_vars, v):
                    var.assign(val)
            else:
                setattr(self, k, v)
