"""Adasum: scale-invariant gradient combination, expressed in XLA.

Parity surface: ``horovod/common/ops/adasum/adasum.h``
(``Adasum<Communicator_type>::DispatchFusedAllreduce`` — recursive
vector-halving distance-doubling with dot-product correction) and the
``op=hvd.Adasum`` argument.

The pairwise rule for two gradients a, b is

    adasum(a, b) = (1 - a·b / (2 a·a)) a + (1 - a·b / (2 b·b)) b

which is symmetric, so both partners of an exchange compute identical
results.  The reference uses vector-halving distance-doubling (VHDD) to
halve wire bytes per hop on low-bandwidth fabrics; on TPU the ICI links
are fast and the latency of 2× the hops dominates, so we use plain
recursive distance-doubling over full vectors with ``lax.ppermute`` —
log2(n) hops, each a single neighbor exchange that XLA schedules on ICI.

Requires a power-of-two axis size (as the reference's recursive
algorithm effectively does per node group); callers fall back to
averaging otherwise.

Hierarchical variant (parity: ``adasum_gpu_operations.cc``): under
``HVTPU_HIERARCHICAL_ALLREDUCE`` with a uniform (dcn, ici) layout the
eager engine sums within each host over ici and runs this combine only
ACROSS hosts (``comm/eager.py`` ``allreduce_hier_adasum``) — the host
count must be a power of two; like the reference, the local stage is a
SUM, so learning-rate scaling by local_size is the caller's
responsibility.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _pairwise_adasum(a, b, segments=None):
    """Combine two gradient vectors.

    ``segments`` — list of (offset, size) — computes the dot-product
    coefficients *per segment*, which is how the reference applies
    Adasum inside a fused buffer (per-tensor ``tensor_counts`` in
    adasum.h DispatchFusedAllreduce): each tensor in the bucket gets
    its own scale correction, so results don't depend on bucketing.
    """
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    if segments is None:
        segments = [(0, af.shape[0])]
    out_parts = []
    for off, size in segments:
        sa = lax.dynamic_slice(af, (off,), (size,))
        sb = lax.dynamic_slice(bf, (off,), (size,))
        ab = jnp.dot(sa, sb)
        aa = jnp.dot(sa, sa)
        bb = jnp.dot(sb, sb)
        ca = jnp.where(aa > 0, ab / (2.0 * aa), 0.0)
        cb = jnp.where(bb > 0, ab / (2.0 * bb), 0.0)
        out_parts.append((1.0 - ca) * sa + (1.0 - cb) * sb)
    out = jnp.concatenate(out_parts) if len(out_parts) > 1 else out_parts[0]
    return out.reshape(a.shape).astype(a.dtype)


def adasum_reduce(x, axis_name: str, axis_size: int, segments=None):
    """Adasum-combine ``x`` across ``axis_name`` inside shard_map/jit.

    ``axis_size`` must be a power of two ≥ 1.  ``segments`` (offset,
    size) pairs apply the combine per-tensor within a fused flat buffer.
    Returns the combined tensor, identical on every participant.
    """
    if axis_size & (axis_size - 1):
        raise ValueError(
            f"Adasum requires a power-of-two world size, got {axis_size}"
        )
    v = x
    dist = 1
    while dist < axis_size:
        # Pairwise exchange with the partner at XOR distance `dist`.
        perm = [(j, j ^ dist) for j in range(axis_size)]
        other = lax.ppermute(v, axis_name, perm)
        v = _pairwise_adasum(v, other, segments)
        dist *= 2
    return v


def adasum_reduce_reference(tensors):
    """Pure-numpy reference for tests: sequential recursive doubling over a
    list of per-rank tensors; returns the combined tensor.
    """
    import numpy as np

    n = len(tensors)
    assert n & (n - 1) == 0
    vals = [np.asarray(t, dtype=np.float64) for t in tensors]
    dist = 1
    while dist < n:
        new = list(vals)
        for j in range(n):
            a, b = vals[j], vals[j ^ dist]
            ab = float((a * b).sum())
            aa = float((a * a).sum())
            bb = float((b * b).sum())
            ca = ab / (2 * aa) if aa > 0 else 0.0
            cb = ab / (2 * bb) if bb > 0 else 0.0
            new[j] = (1 - ca) * a + (1 - cb) * b
        vals = new
        dist *= 2
    return vals[0]
