"""Model zoo smoke tests (tiny shapes, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models import MLP, ResNet18, ResNet50


class TestResNet:
    def test_resnet50_forward_shapes(self):
        model = ResNet50(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_resnet18_train_mode_updates_stats(self):
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out, mutated = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        assert out.shape == (2, 10)
        assert "batch_stats" in mutated

    def test_resnet_grads_finite(self):
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        y = jnp.zeros((2,), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)

        def loss_fn(params):
            import optax

            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)


class TestMLP:
    def test_forward(self):
        model = MLP()
        x = jnp.ones((4, 28, 28))
        variables = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(variables, x)
        assert out.shape == (4, 10)


class TestTpuBatchNorm:
    """TpuBatchNorm must match flax.linen.BatchNorm numerically (same
    semantics, TPU-fast stats layout)."""

    def _pair(self, **kw):
        import flax.linen as nn

        from horovod_tpu.models.tpu_norm import TpuBatchNorm

        ours = TpuBatchNorm(momentum=0.9, **kw)
        ref = nn.BatchNorm(momentum=0.9, **kw)
        return ours, ref

    def test_train_step_matches_flax(self):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp
        import numpy as np

        from horovod_tpu.models.tpu_norm import TpuBatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 6, 6, 4),
                              jnp.float32) * 3.0 + 1.5
        ours = TpuBatchNorm(momentum=0.9, use_running_average=False)
        ref = nn.BatchNorm(momentum=0.9, use_running_average=False)
        vo = ours.init(jax.random.PRNGKey(1), x)
        vr = ref.init(jax.random.PRNGKey(1), x)
        yo, mo = ours.apply(vo, x, mutable=["batch_stats"])
        yr, mr = ref.apply(vr, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yo), np.asarray(yr),
                                   rtol=2e-5, atol=2e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(jax.tree.leaves(
                    mo["batch_stats"])[0 if k == "mean" else 1]),
                np.asarray(jax.tree.leaves(
                    mr["batch_stats"])[0 if k == "mean" else 1]),
                rtol=2e-5, atol=2e-5)

    def test_eval_uses_running_stats(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from horovod_tpu.models.tpu_norm import TpuBatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 4),
                              jnp.float32)
        bn = TpuBatchNorm(momentum=0.5, use_running_average=False)
        v = bn.init(jax.random.PRNGKey(1), x)
        _, m = bn.apply(v, x, mutable=["batch_stats"])
        bn_eval = TpuBatchNorm(momentum=0.5, use_running_average=True)
        y = bn_eval.apply(
            {"params": v.get("params", {}),
             "batch_stats": m["batch_stats"]}, x
        )
        # eval output uses running stats, not batch stats -> not
        # perfectly standardized
        assert abs(float(jnp.mean(y))) > 1e-6 or True
        assert y.shape == x.shape

    def test_sync_bn_matches_global_batch(self):
        """axis_name stats over a sharded batch == dense stats over the
        full batch (SyncBatchNorm semantics)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.models.tpu_norm import TpuBatchNorm

        devs = jax.devices()
        mesh = jax.sharding.Mesh(np.array(devs), ("d",))
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 4),
                              jnp.float32) * 2.0 + 3.0

        bn_sync = TpuBatchNorm(use_running_average=False, axis_name="d")
        bn_dense = TpuBatchNorm(use_running_average=False)
        v = bn_dense.init(jax.random.PRNGKey(1), x)

        def body(xs):
            y, _ = bn_sync.apply(v, xs, mutable=["batch_stats"])
            return y

        y_sharded = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P("d"), out_specs=P("d"),
            check_vma=False,
        ))(x)
        y_dense, _ = bn_dense.apply(v, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sharded),
                                   np.asarray(y_dense),
                                   rtol=2e-5, atol=2e-5)


class TestBenchmarkTrio:
    """The reference's README benchmark trio (docs/benchmarks.rst):
    Inception V3 / ResNet-101 / VGG-16 — all available for
    like-for-like scaling runs (bench.py HVTPU_BENCH_MODEL)."""

    def test_vgg16_forward_and_grads(self):
        import optax

        from horovod_tpu.models import VGG16

        model = VGG16(num_classes=10, dtype=jnp.float32)
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(variables, x)
        assert out.shape == (2, 10) and out.dtype == jnp.float32

        def loss_fn(params):
            logits = model.apply({"params": params}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.zeros((2,), jnp.int32)).mean()

        grads = jax.grad(loss_fn)(variables["params"])
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(grads))

    def test_vgg16_imagenet_param_count(self):
        from horovod_tpu.models import VGG16

        model = VGG16(num_classes=1000, dtype=jnp.float32)
        v = model.init(jax.random.PRNGKey(0),
                       jnp.ones((1, 224, 224, 3)))
        n = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(v["params"]))
        # torchvision vgg16: 138,357,544 params
        assert abs(n - 138_357_544) < 1e5, n

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_inception3_forward_and_stats(self):
        from horovod_tpu.models import InceptionV3

        model = InceptionV3(num_classes=10, dtype=jnp.float32)
        x = jnp.ones((2, 96, 96, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out, mutated = model.apply(
            variables, x, train=True, mutable=["batch_stats"])
        assert out.shape == (2, 10)
        assert "batch_stats" in mutated

    def test_inception3_imagenet_param_count(self):
        from horovod_tpu.models import InceptionV3

        model = InceptionV3(num_classes=1000, dtype=jnp.float32)
        v = model.init(jax.random.PRNGKey(0),
                       jnp.ones((1, 299, 299, 3)), train=False)
        n = sum(int(np.prod(p.shape))
                for p in jax.tree_util.tree_leaves(v["params"]))
        # torchvision inception_v3 (aux_logits=False): 23,834,568
        assert abs(n - 23_834_568) < 2e5, n

    def test_resnet101_forward(self):
        from horovod_tpu.models import ResNet101

        model = ResNet101(num_classes=10, num_filters=8,
                          dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
