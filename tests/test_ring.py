"""Pallas ring collective kernels (ops/ring.py) — the NCCL-ring analog
(horovod/common/ops/nccl_operations.cc ring allreduce) hand-rolled over
ICI remote DMA.

On this CPU test platform the REAL kernel bodies run under the Pallas
TPU interpreter, which simulates the remote DMAs + semaphores across
the 8 shard_map devices — so the double-buffer protocol, the per-slot
semaphore accounting, and the ACK backpressure all actually execute.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.ring import ring_allgather_2d, ring_allreduce

AXIS = "x"


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setenv("HVTPU_PALLAS_INTERPRET", "1")


def mesh8():
    return Mesh(np.array(jax.devices()), (AXIS,))


def _run(body, *args, out_specs):
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh8(),
            in_specs=tuple(P(AXIS) for _ in args),
            out_specs=out_specs, check_vma=False,
        )
    )(*args)


class TestRingAllreduce:
    @pytest.mark.parametrize("per_rank", [1024, 4000, 5])
    def test_matches_psum(self, per_rank):
        x = jnp.asarray(
            np.random.RandomState(per_rank).randn(8, per_rank)
            .astype(np.float32)
        )
        out = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS),
            x, out_specs=P(),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
        )

    def test_average(self):
        x = jnp.asarray(
            np.random.RandomState(1).randn(8, 2048).astype(np.float32)
        )
        out = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS, average=True),
            x, out_specs=P(),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x).mean(0), rtol=1e-5, atol=1e-6
        )

    def test_integer_dtype_consistent_across_backends(self, monkeypatch):
        # ints must take the exact psum path with the SAME dtype no
        # matter which backend flag is set (regression: pallas path
        # returned f32 for ints)
        x = jnp.asarray(
            np.arange(8 * 64, dtype=np.int32).reshape(8, 64)
        )
        out_pallas = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS),
            x, out_specs=P(),
        )
        monkeypatch.setenv("HVTPU_PALLAS", "0")
        out_psum = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS),
            x, out_specs=P(),
        )
        assert out_pallas.dtype == out_psum.dtype == jnp.int32
        np.testing.assert_array_equal(
            np.asarray(out_pallas), np.asarray(out_psum)
        )

    def test_nd_shape_and_dtype_restore(self):
        x = jnp.asarray(
            np.random.RandomState(2).randn(8, 10, 33).astype(np.float32)
        ).astype(jnp.bfloat16)
        out = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS),
            x, out_specs=P(),
        )
        assert out.dtype == jnp.bfloat16
        assert out.shape == (10, 33)
        want = np.asarray(x.astype(jnp.float32)).sum(0)
        np.testing.assert_allclose(
            np.asarray(out.astype(jnp.float32)), want, rtol=0.05, atol=0.2
        )

    def test_quantized_per_hop(self):
        """The EQuARX proper: int8 wire on every hop.  Error bound: one
        quantization step per hop, 2(N-1) hops."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(8, 4096).astype(np.float32))
        out = _run(
            lambda xs: ring_allreduce(
                xs[0], axis_name=AXIS, quantized=True
            ),
            x, out_specs=P(),
        )
        want = np.asarray(x).sum(0)
        err = np.abs(np.asarray(out) - want)
        # generous per-hop bound: 14 hops x (running absmax / 127)
        bound = 14 * np.abs(np.asarray(x)).sum(0).max() / 127
        assert err.max() <= bound, (err.max(), bound)
        # and it must be far better than not reducing at all
        assert err.mean() < 0.1

    def test_quantized_identical_on_every_rank(self):
        """The allreduce contract: every rank must hold bit-identical
        output.  Regression for the per-hop-requantizing all-gather,
        where the chunk owner kept its raw f32 accumulator while peers
        got quantize round-trips that drifted with ring distance —
        replica parameters silently diverged in DP training."""
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(8, 4096).astype(np.float32))
        # collect each rank's full output instead of letting shard_map
        # assume replication
        per_rank = _run(
            lambda xs: ring_allreduce(
                xs[0], axis_name=AXIS, quantized=True
            )[None],
            x, out_specs=P(AXIS),
        )
        got = np.asarray(per_rank)
        assert got.shape[0] == 8
        for r in range(1, 8):
            np.testing.assert_array_equal(got[0], got[r])


class TestRingAllgather:
    def test_matches_all_gather(self):
        x = jnp.asarray(
            np.random.RandomState(4).randn(8 * 16, 128).astype(np.float32)
        )

        def body(xs):
            return ring_allgather_2d(xs, axis_name=AXIS)

        out = _run(body, x, out_specs=P())
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


class TestFallbacks:
    def test_no_pallas_falls_back_to_psum(self, monkeypatch):
        monkeypatch.setenv("HVTPU_PALLAS", "0")
        x = jnp.asarray(
            np.random.RandomState(5).randn(8, 100).astype(np.float32)
        )
        out = _run(
            lambda xs: ring_allreduce(xs[0], axis_name=AXIS),
            x, out_specs=P(),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x).sum(0), rtol=1e-6
        )

    def test_no_pallas_quantized_falls_back_to_xla_path(self, monkeypatch):
        monkeypatch.setenv("HVTPU_PALLAS", "0")
        x = jnp.asarray(
            np.random.RandomState(6).randn(8, 2048).astype(np.float32)
        )
        out = _run(
            lambda xs: ring_allreduce(
                xs[0], axis_name=AXIS, quantized=True
            ),
            x, out_specs=P(),
        )
        want = np.asarray(x).sum(0)
        amax = np.abs(np.asarray(x)).max()
        assert np.abs(np.asarray(out) - want).max() <= 8 * 3 * amax / 127


class TestEngineIntegration:
    def test_int8_engine_path_routes_through_ring(self, monkeypatch):
        """HVTPU_QUANTIZED_RING=1: spmd.allreduce with int8 compression
        executes the per-hop requantizing ring kernel."""
        monkeypatch.setenv("HVTPU_QUANTIZED_RING", "1")
        from horovod_tpu.comm import spmd
        from horovod_tpu.comm.compression import Compression
        from horovod_tpu.comm.reduce_ops import ReduceOp
        from horovod_tpu.ops import ring as ring_mod

        if ring_mod._interpret_arg() is None:
            pytest.skip("Pallas interpreter cannot run the ring kernels "
                        "on this jax (no remote-DMA simulation); the "
                        "engine correctly falls back to the XLA path")

        # the XLA two-phase path would also satisfy the numeric bound,
        # so additionally prove the ring kernel actually ran
        calls = []
        real = ring_mod.ring_allreduce
        monkeypatch.setattr(
            ring_mod, "ring_allreduce",
            lambda *a, **kw: (calls.append(kw), real(*a, **kw))[1],
        )

        x = jnp.asarray(
            np.random.RandomState(8).randn(8, 2048).astype(np.float32)
        )
        out = _run(
            lambda xs: spmd.allreduce(
                xs[0], axis_name=AXIS, op=ReduceOp.SUM,
                compression=Compression.int8,
            ),
            x, out_specs=P(),
        )
        assert calls and calls[0].get("quantized") is True
        want = np.asarray(x).sum(0)
        err = np.abs(np.asarray(out) - want)
        bound = 14 * np.abs(np.asarray(x)).sum(0).max() / 127
        assert err.max() <= bound
        assert err.mean() < 0.1
