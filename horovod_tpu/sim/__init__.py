"""Fabric simulator: the real control plane at virtual scale.

One process hosts 256–4096 virtual ranks on a deterministic
discrete-event kernel (virtual time, no real sleeps); each rank runs
REAL framework code — eager negotiation over KVTransport, the drain
coordination protocol, rendezvous audits, heartbeat stall inspection,
HostManager blacklisting — against an in-memory coordination KV with
per-link latency/bandwidth/jitter models, under chaos injected through
``core/faults.py``.  Same seed ⇒ byte-identical event log.

Entry points: ``python -m tools.hvtpusim`` (CLI) and
:func:`~horovod_tpu.sim.scenarios.run_scenario` (tests).  Architecture
and the determinism/replay contract: docs/simulation.md.
"""

from .context import RankContext
from .fabric import LinkModel, SimFabric
from .kernel import (DeadlockError, SimKernel, SimTimeBudgetExceeded,
                     VirtualClock, VirtualExit, WaitToken)
from .scenarios import SCENARIOS, run_scenario
from .workers import (SimElasticState, WorldView, elect_and_assign,
                      patch_data_plane)

__all__ = [
    "DeadlockError",
    "LinkModel",
    "RankContext",
    "SCENARIOS",
    "SimElasticState",
    "SimFabric",
    "SimKernel",
    "SimTimeBudgetExceeded",
    "VirtualClock",
    "VirtualExit",
    "WaitToken",
    "WorldView",
    "elect_and_assign",
    "patch_data_plane",
    "run_scenario",
]
