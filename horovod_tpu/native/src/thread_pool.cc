#include "thread_pool.h"

#include <algorithm>
#include <cstring>

namespace hvt {

ThreadPool::ThreadPool(int num_threads) {
  num_threads = std::max(1, num_threads);
  workers_.reserve(num_threads);
  for (int i = 0; i < num_threads; ++i) {
    workers_.emplace_back([this] { Loop(); });
  }
}

ThreadPool::~ThreadPool() {
  {
    std::lock_guard<std::mutex> g(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (std::thread& t : workers_) t.join();
}

void ThreadPool::Loop() {
  for (;;) {
    std::function<void()> task;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
      if (stop_ && tasks_.empty()) return;
      task = std::move(tasks_.front());
      tasks_.pop();
    }
    task();
    {
      std::lock_guard<std::mutex> g(mu_);
      if (--outstanding_ == 0) done_cv_.notify_all();
    }
  }
}

void ThreadPool::ParallelFor(int64_t n, const std::function<void(int64_t)>& fn) {
  if (n <= 0) return;
  if (n == 1 || workers_.empty()) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    outstanding_ += n;
    for (int64_t i = 0; i < n; ++i) {
      tasks_.push([&fn, i] { fn(i); });
    }
  }
  cv_.notify_all();
  std::unique_lock<std::mutex> lk(mu_);
  done_cv_.wait(lk, [this] { return outstanding_ == 0; });
}

ThreadPool& GlobalPool() {
  static ThreadPool pool(
      std::max(2u, std::thread::hardware_concurrency() / 2));
  return pool;
}

}  // namespace hvt
