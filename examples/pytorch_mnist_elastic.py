"""Elastic torch training example — the horovod_tpu analog of the
reference's examples/elastic/pytorch/pytorch_mnist_elastic.py:
``hvd.elastic.run`` with ``TorchState`` (model + optimizer) and the
``ElasticSampler``; commits survive worker loss and world resizes.

Run:
  hvtpurun --host-discovery-script ./discover.sh --min-np 2 \
      --cpu-devices 1 python examples/pytorch_mnist_elastic.py
where discover.sh prints e.g. "localhost:4".
"""

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    hvd.init()
    torch.manual_seed(42)

    rng = np.random.RandomState(0)
    x = torch.from_numpy(rng.rand(1024, 784).astype(np.float32))
    w = rng.randn(784, 10).astype(np.float32)
    y = torch.from_numpy((x.numpy() @ w).argmax(axis=1))

    model = Net()
    # elastic: lr scales with the CURRENT size; rebuilt on reset
    opt = torch.optim.SGD(model.parameters(), lr=0.05 * hvd.size())
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())

    dataset = torch.utils.data.TensorDataset(x, y)
    sampler = hvd.elastic.ElasticSampler(dataset, shuffle=True)
    state = hvd.elastic.TorchState(
        model=model, optimizer=opt, sampler=sampler, epoch=0)

    def on_reset():
        for g in opt.param_groups:
            g["lr"] = 0.05 * hvd.size()

    state.register_reset_callbacks([on_reset])
    batch = 64
    epochs = 6

    @hvd.elastic.run
    def train(state):
        while state.epoch < epochs:
            sampler.set_epoch(state.epoch)
            loader = torch.utils.data.DataLoader(
                dataset, batch_size=batch, sampler=sampler)
            total, steps = 0.0, 0
            for bi, (bx, by) in enumerate(loader):
                opt.zero_grad()
                loss = F.nll_loss(model(bx), by)
                loss.backward()
                opt.step()
                sampler.record_batch(bi, batch)
                total += float(loss)
                steps += 1
            avg = hvd.allreduce(
                torch.tensor(total / max(steps, 1)), op=hvd.Average)
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(avg):.4f} "
                      f"(world size {hvd.size()})", flush=True)
            state.epoch += 1
            state.commit()

    train(state)
    if hvd.rank() == 0:
        print(f"done; ranks consistent ({hvd.size()} ranks)",
              flush=True)


if __name__ == "__main__":
    main()
