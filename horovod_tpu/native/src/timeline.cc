#include "timeline.h"

namespace hvt {

static std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

TimelineWriter::TimelineWriter(const std::string& path, int32_t rank)
    : rank_(rank) {
  f_ = fopen(path.c_str(), "w");
  if (f_) fputs("[\n", f_);
}

TimelineWriter::~TimelineWriter() {
  if (f_) {
    // Chrome tracing tolerates a missing closing bracket (crash-safe
    // appends, same property the reference relies on); close properly.
    fputs("\n]\n", f_);
    fclose(f_);
  }
}

void TimelineWriter::Event(const std::string& name, char ph,
                           const std::string& category, double ts_us,
                           double dur_us) {
  if (!f_) return;
  std::lock_guard<std::mutex> g(mu_);
  if (!first_) fputs(",\n", f_);
  first_ = false;
  if (ph == 'X') {
    fprintf(f_,
            "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,"
            "\"dur\":%.3f,\"pid\":%d,\"tid\":0}",
            JsonEscape(name).c_str(), JsonEscape(category).c_str(), ts_us,
            dur_us, rank_);
  } else {
    fprintf(f_,
            "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"%c\",\"ts\":%.3f,"
            "\"pid\":%d,\"tid\":0}",
            JsonEscape(name).c_str(), JsonEscape(category).c_str(), ph, ts_us,
            rank_);
  }
}

void TimelineWriter::MarkCycle(double ts_us) {
  // Parity: HOROVOD_TIMELINE_MARK_CYCLES instant events.
  Event("CYCLE", 'i', "cycle", ts_us);
}

void TimelineWriter::Flush() {
  std::lock_guard<std::mutex> g(mu_);
  if (f_) fflush(f_);
}

}  // namespace hvt
