"""Native (C++) control-plane core + pure-Python twin.

Layout (parity: the reference's C++ core horovod/common/* built by
CMake into the framework .so; see SURVEY.md §2.1):

- ``src/``        C++17 sources → ``libhvt_core.so`` (built on demand)
- ``core.py``     ctypes bindings (parity: basics.py ctypes loading)
- ``wire.py``     Python mirror of the coordination wire format
- ``fallback.py`` pure-Python controller with identical bytes/semantics

``make_controller`` picks the native implementation when a toolchain is
available, else the fallback — both speak the same wire format, so
mixed fleets coordinate fine.
"""

from __future__ import annotations

import os

from . import core, fallback, wire


def native_available() -> bool:
    return core.available()


def make_controller(rank: int, size: int, fusion_threshold: int,
                    cache_capacity: int = 1024, stall_warn_s: float = 60.0,
                    stall_abort_s: float = 0.0,
                    resync_every: int = None):
    """Controller factory: native if buildable, else Python fallback.
    ``HVTPU_FORCE_PY_CONTROLLER=1`` forces the fallback (tests use this
    to cross-check both).  ``resync_every`` is the steady-state bypass
    cadence (every Nth all-cache-hit cycle sends a full resync blob; 0
    disables bypass); defaults to ``HVTPU_CACHE_RESYNC_EVERY`` or 64.
    Every rank must agree on the value — it shapes the wire traffic
    pattern, not the decisions, so the launcher env is the natural
    distribution channel."""
    if resync_every is None:
        resync_every = int(os.environ.get("HVTPU_CACHE_RESYNC_EVERY", "64"))
    if (not os.environ.get("HVTPU_FORCE_PY_CONTROLLER")
            and core.available()):
        return core.NativeController(
            rank, size, fusion_threshold, cache_capacity,
            stall_warn_s, stall_abort_s, resync_every=resync_every,
        )
    return fallback.PyController(
        rank, size, fusion_threshold, cache_capacity,
        stall_warn_s, stall_abort_s, resync_every=resync_every,
    )


__all__ = [
    "core", "fallback", "wire", "native_available", "make_controller",
]
