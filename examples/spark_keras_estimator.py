"""Spark KerasEstimator example — the horovod_tpu port surface of the
reference's examples/spark/keras estimators: DataFrame in, trained
model out, transform to predictions.  Pandas frames here (pyspark
works when installed); ranks are real worker processes.

Run:  python examples/spark_keras_estimator.py
"""

import argparse
import tempfile

import numpy as np
import pandas as pd

from horovod_tpu.spark import KerasEstimator, LocalStore


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-proc", type=int, default=2)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    import keras

    rng = np.random.RandomState(0)
    x = rng.rand(args.train_size, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)
    df = pd.DataFrame({"features": list(x), "label": y})

    model = keras.Sequential([
        keras.layers.Input((784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    with tempfile.TemporaryDirectory() as store_dir:
        est = KerasEstimator(
            model=model,
            optimizer=keras.optimizers.SGD(learning_rate=0.1),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
            feature_cols=["features"],
            label_cols=["label"],
            validation=0.1,
            batch_size=args.batch_size,
            epochs=args.epochs,
            num_proc=args.num_proc,
            store=LocalStore(store_dir),
            random_seed=42,
            verbose=0,
        )
        trained = est.fit(df)
        hist = trained.getHistory()
        print(f"loss history: {[round(v, 4) for v in hist['loss']]}")
        print(f"val_accuracy: "
              f"{[round(v, 4) for v in hist['val_accuracy']]}")

        out = trained.transform(df)
        pred = np.stack(out["label__output"].to_numpy()).argmax(axis=1)
        acc = float((pred == y).mean())
        print(f"train accuracy after transform: {acc:.3f}")
        assert hist["loss"][-1] < hist["loss"][0]
        assert acc > 0.6
        print(f"estimator OK ({args.num_proc} ranks)")


if __name__ == "__main__":
    main()
