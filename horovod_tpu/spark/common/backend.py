"""Execution backends for the Spark estimators.

Parity surface: ``horovod/spark/common/backend.py`` (``Backend``,
``SparkBackend``) — the reference's Backend answers two questions for
an estimator: how many training processes, and "run this function on
all of them and give me the per-rank results".

TPU-native scope: ranks are placed by the hvtpurun launcher (one per
local worker process; on a real pod, one per host×chip via the same
launcher over ssh), not by Spark executor placement — SURVEY §7.3.
``LocalBackend`` is therefore the real implementation;
``SparkBackend`` probes for pyspark, reads its parallelism for the
default ``num_proc``, and executes through the same launcher in local
mode (the reference's own CI runs its estimators on local-mode Spark).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class Backend:
    """run(fn) across ranks + num_processes (reference Backend ABC)."""

    def num_processes(self) -> int:
        raise NotImplementedError

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None,
            env: Optional[Dict[str, str]] = None) -> List[Any]:
        raise NotImplementedError


class LocalBackend(Backend):
    """Estimator execution over the hvtpurun local launcher: real
    worker processes, real cross-process collectives (XLA CPU when
    ``cpu_devices`` is set, the accelerator otherwise)."""

    def __init__(self, num_proc: int = 2,
                 cpu_devices: Optional[int] = 1,
                 start_timeout: Optional[float] = None,
                 verbose: bool = False):
        self._np = num_proc
        self._cpu_devices = cpu_devices
        self._start_timeout = start_timeout
        self._verbose = verbose

    def num_processes(self) -> int:
        return self._np

    def run(self, fn, args=(), kwargs=None, env=None):
        from ... import runner

        return runner.run(
            fn, args=args, kwargs=kwargs, np=self._np,
            cpu_devices=self._cpu_devices, env=env,
            start_timeout=self._start_timeout, verbose=self._verbose,
        )


class SparkBackend(LocalBackend):
    """pyspark-aware backend: takes ``num_proc`` from the active
    SparkSession's default parallelism when not given, then executes
    through the local launcher (executor placement is out of scope —
    SURVEY §7.3; the coordination/collective fabric is the launcher's
    either way)."""

    def __init__(self, num_proc: Optional[int] = None, **kwargs):
        if num_proc is None:
            num_proc = self._spark_parallelism() or 2
        super().__init__(num_proc=num_proc, **kwargs)

    @staticmethod
    def _spark_parallelism() -> Optional[int]:
        try:
            from pyspark.sql import SparkSession
        except ImportError:
            return None
        session = SparkSession.getActiveSession()
        if session is None:
            return None
        return session.sparkContext.defaultParallelism
