"""Every way simulator code can leak host time or ambient RNG."""

import random
import time
from random import randint
from time import sleep as zzz


def naughty():
    t0 = time.time()          # host wall clock
    t1 = time.monotonic()     # host monotonic clock
    time.sleep(0.1)           # real sleep
    time.sleep(0.2)           # second hit: occurrence-indexed key
    zzz(0.3)                  # from-import alias of time.sleep
    x = random.random()       # process-global RNG
    random.seed(42)           # reseeding the global RNG
    y = randint(0, 9)         # from-import of a module-level fn
    ok = random.Random(7).random()  # allowed: seeded instance
    return t0 + t1 + x + y + ok
