"""Gradient compression for the wire.

Parity surface: ``horovod/torch/compression.py`` /
``horovod/tensorflow/compression.py`` — the pluggable ``Compression``
namespace with ``none`` and ``fp16`` compressors exposing
``compress(tensor) -> (tensor, ctx)`` / ``decompress(tensor, ctx)``.

TPU-native notes: compressors are pure jax functions, so they fuse into
the surrounding XLA program (the cast rides the same HBM pass as the
bucket flatten).  ``bf16`` is added because bfloat16 is the TPU wire
format of choice (same 2× saving as fp16, no range loss), and ``int8``
implements EQuARX-style quantized allreduce (PAPERS.md) with per-chunk
scales.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp
from jax import lax

_STOCH_CALL_COUNTER = itertools.count()


def _rank_salt() -> int:
    try:
        from ..core import state as _core_state

        rank = _core_state.global_state().rank if _core_state.initialized() else 0
    except Exception:  # pragma: no cover - state not importable
        rank = 0
    return (rank * 1_000_003) & 0x7FFFFFFF


def _stochastic_seed(flat):
    """Stochastic-rounding seed: a TRACED fold of the payload bits,
    salted by the process rank and a per-call counter.

    The payload fold must be traced — a Python-side value alone is
    evaluated once at trace time and bakes into the compiled program,
    giving identical dither every step.  The fold reads the payload in
    its NATIVE width (bitcast, no f32 astype) so seed derivation never
    materializes a widened copy of a bf16/f16 buffer in HBM.  The
    per-call counter varies eager-path calls even for byte-identical
    payloads; under jit it is a baked constant, so a payload that
    repeats exactly across steps repeats its dither — callers needing
    per-step variation for constant payloads must vary the payload or
    use the allreduce-wire path (comm/quantized.py), which folds the
    collective's rank index.  The rank salt decorrelates
    multi-controller processes; in single-controller shard_map it is
    the same on every shard, so identical payloads on two shards dither
    identically (the wire path again decorrelates by axis_index)."""
    if flat.dtype.itemsize == 2:
        bits = lax.bitcast_convert_type(flat, jnp.int16).astype(jnp.int32)
    elif flat.dtype == jnp.float32:
        bits = lax.bitcast_convert_type(flat, jnp.int32)
    else:
        bits = lax.bitcast_convert_type(
            flat.astype(jnp.float32), jnp.int32)
    salt = (_rank_salt() ^ (next(_STOCH_CALL_COUNTER) * 0x9E3779B1)) & 0x7FFFFFFF
    return jnp.sum(bits, dtype=jnp.int32) ^ jnp.int32(salt)


class Compressor:
    """Interface: compress before the collective, decompress after."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @staticmethod
    def wire_dtype(dtype):
        """Dtype that actually crosses the wire for an input of `dtype`
        (the fusion/caching signature — fusion_buffer_manager.cc keys
        buffers on the buffer dtype, not the framework dtype)."""
        return dtype


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 on the wire, back to original dtype after."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor

    @staticmethod
    def wire_dtype(dtype):
        return jnp.float16 if jnp.issubdtype(dtype, jnp.floating) else dtype


class BF16Compressor(Compressor):
    """bfloat16 wire format — the TPU-idiomatic 2× compression."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor

    @staticmethod
    def wire_dtype(dtype):
        return jnp.bfloat16 if jnp.issubdtype(dtype, jnp.floating) else dtype


class Int8Compressor(Compressor):
    """Block-scaled int8 quantization (EQuARX-style, PAPERS.md).

    Tensors are quantized in chunks of ``BLOCK`` elements with a per-chunk
    absmax scale carried alongside in fp32.  4× wire saving for the
    payload; the scales add 4/BLOCK bytes/element.  Intended for the
    fused-bucket path where tensors are large and flat.
    """

    BLOCK = 1024
    STOCHASTIC = False

    @classmethod
    def compress(cls, tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        orig_dtype = tensor.dtype
        orig_shape = tensor.shape
        # One-pass Pallas quantize kernel on TPU (ops/pallas_ops.py —
        # the analog of the reference's cuda_kernels.cu scale kernels);
        # numerically-identical XLA lowering elsewhere.  Kernel layout
        # (rows, 128) int8 + (rows/8, 1) scales is row-major-identical
        # to this class's (nblocks, BLOCK=1024) wire format.
        from ..ops import quantize_int8_blocks

        flat = tensor.reshape(-1)
        q, scale, n = quantize_int8_blocks(
            flat,
            stochastic=cls.STOCHASTIC,
            seed=_stochastic_seed(flat) if cls.STOCHASTIC else 0,
        )
        q = q.reshape(-1, Int8Compressor.BLOCK)
        return q, (orig_dtype, orig_shape, n, scale)

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        orig_dtype, orig_shape, n, scale = ctx
        from ..ops import dequantize_int8_blocks

        deq = dequantize_int8_blocks(
            tensor.reshape(-1, 128), scale, n, dtype=jnp.float32
        )
        return deq.reshape(orig_shape).astype(orig_dtype)

    @staticmethod
    def wire_dtype(dtype):
        return jnp.int8 if jnp.issubdtype(dtype, jnp.floating) else dtype


class Int8StochasticCompressor(Int8Compressor):
    """Int8 with stochastic rounding via the on-core TPU PRNG
    (ops/pallas_ops.py): unbiased quantisation noise, so rounding error
    does not accumulate over ranks when the wire feeds a summation —
    the error model EQuARX (PAPERS.md, arXiv:2506.17615) assumes.
    Falls back to deterministic rounding off-TPU."""

    STOCHASTIC = True


class Compression:
    """Namespace matching the reference API: ``Compression.none`` etc."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int8_stochastic = Int8StochasticCompressor

    @staticmethod
    def from_name(name: str):
        try:
            return {
                "none": NoneCompressor,
                "fp16": FP16Compressor,
                "bf16": BF16Compressor,
                "int8": Int8Compressor,
                "int8_stochastic": Int8StochasticCompressor,
            }[name]
        except KeyError:
            raise ValueError(f"unknown compression {name!r}") from None
