"""Wire-byte accounting: compression must SHRINK what crosses the link.

The reference's claim is 'fp16 compression: up to ~2x on comm-bound
models' (BASELINE.md).  Correctness of compress/decompress is covered
elsewhere; these tests pin the *bytes* story so the feature's value is
measurable, not asserted:

- HLO-level: lower the jitted SPMD allreduce and assert the
  ``all-reduce`` op's operand element type is the WIRE dtype — f16/bf16
  under 2-byte compression (half the f32 bytes), 8-bit codes under
  int8.  XLA moves exactly the lowered operand over ICI, so this is
  the strongest available proof without hardware link counters.
- Fusion-level: a compressed fused bucket's wire buffer is half (fp16)
  / about a quarter (int8 + scale sidecar) of the f32 payload bytes.

The throughput side of the story is ``bench_eager.py --compression-ab``
(BENCH_EAGER.json, P=4 real processes).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.comm import spmd
from horovod_tpu.comm.compression import Compression
from horovod_tpu.comm.reduce_ops import ReduceOp


def _lowered_allreduce_text(compression, dtype=jnp.float32, n=4096):
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("dp",))

    def body(x):
        return spmd.allreduce(x, axis_name="dp", op=ReduceOp.SUM,
                              compression=compression)

    fn = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
        check_vma=False))
    x = jnp.zeros((8 * n,), dtype)
    return fn.lower(x).as_text()


def _allreduce_operand_types(text):
    """Element types fed to all-reduce ops in the lowered module (the
    operand signature sits on the op region's closing line — the
    StableHLO all_reduce is multi-line)."""
    import re

    types = []
    for m in re.finditer(
            r"stablehlo\.all_reduce.*?\}\)\s*:\s*\((.*?)\)\s*->",
            text, re.S):
        types.extend(re.findall(
            r"tensor<(?:\d+x)*([a-z]+\d+)>", m.group(1)))
    return types


class TestWireDtypeInHLO:
    def test_uncompressed_wire_is_f32(self):
        text = _lowered_allreduce_text(Compression.none)
        types = _allreduce_operand_types(text)
        assert types and all(t == "f32" for t in types), types

    def test_fp16_wire_halves_bytes(self):
        text = _lowered_allreduce_text(Compression.fp16)
        types = _allreduce_operand_types(text)
        assert types and all(t == "f16" for t in types), types

    def test_bf16_wire_halves_bytes(self):
        text = _lowered_allreduce_text(Compression.bf16)
        types = _allreduce_operand_types(text)
        assert types and all(t == "bf16" for t in types), types

    def test_int8_wire_quarters_payload(self):
        """int8 lowers to the two-phase quantized exchange (store-and-
        forward all_to_all + all_gather of i8 CODES, with scalar f32
        scale sidecars) — no f32-payload all-reduce may remain, and
        f32 bytes on the wire must be a sliver of the i8 code bytes."""
        import re

        text = _lowered_allreduce_text(Compression.int8)
        assert not _allreduce_operand_types(text), (
            "int8 path should not lower to a dense all-reduce")
        i8_bytes = f32_bytes = 0
        for line in text.splitlines():
            if "all_to_all" not in line and "all_gather" not in line:
                continue
            for shape, t in re.findall(
                    r"tensor<((?:\d+x)*)([a-z]+\d+)>", line):
                if t == "i64":  # replica_groups attribute, not payload
                    continue
                n = int(np.prod([int(d) for d in
                                 shape.rstrip("x").split("x") or [1]]))
                if t == "i8":
                    i8_bytes += n
                elif t == "f32":
                    f32_bytes += n * 4
        assert i8_bytes > 0
        # sidecar scales are per-chunk scalars: far under 5% of codes
        assert f32_bytes < 0.05 * i8_bytes, (i8_bytes, f32_bytes)


class TestFusedBufferBytes:
    def _fused_wire_nbytes(self, compression):
        from horovod_tpu.comm.packing import pack_flat

        tensors = [jnp.ones((1024,), jnp.float32) for _ in range(8)]
        flat, _ = pack_flat(tensors)
        wire, _ctx = compression.compress(flat)
        sidecar = 0
        if isinstance(_ctx, (tuple, list)):
            sidecar = sum(
                int(np.prod(c.shape)) * c.dtype.itemsize
                for c in _ctx if hasattr(c, "dtype"))
        return wire.nbytes + sidecar, flat.nbytes

    def test_fp16_fused_bucket_is_half(self):
        wire, payload = self._fused_wire_nbytes(Compression.fp16)
        assert wire == payload // 2

    def test_bf16_fused_bucket_is_half(self):
        wire, payload = self._fused_wire_nbytes(Compression.bf16)
        assert wire == payload // 2

    def test_int8_fused_bucket_is_quarterish(self):
        wire, payload = self._fused_wire_nbytes(Compression.int8)
        # 1 byte/element + per-chunk scale sidecar: ≤ 30% of f32
        assert wire <= payload * 0.30, (wire, payload)

    def test_none_is_identity(self):
        wire, payload = self._fused_wire_nbytes(Compression.none)
        assert wire == payload
