"""bench.py's round-over-round regression floors (VERDICT r4 #4):
BENCH_MODELS.json bar.floors are enforced by the bench harness — a
deliberate 3% slowdown in any benchmarked model fails the run."""

import json
import os
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


@pytest.fixture
def bench():
    import importlib

    import bench as bench_mod

    return importlib.reload(bench_mod)


class TestRegressionFloor:
    def test_floors_recorded_for_all_models(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            bar = json.load(f)["bar"]
        assert set(bar["floors"]) == set(bench.MODELS)
        assert 0 < bar["tolerance"] < 0.1

    def test_within_tolerance_passes(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            floors = json.load(f)["bar"]["floors"]
        for model, floor in floors.items():
            assert bench.check_regression_floor(
                model, floor * 0.99, _ROOT) is None
            assert bench.check_regression_floor(
                model, floor * 1.10, _ROOT) is None

    def test_three_percent_slowdown_fails(self, bench):
        with open(os.path.join(_ROOT, "BENCH_MODELS.json")) as f:
            floors = json.load(f)["bar"]["floors"]
        for model, floor in floors.items():
            err = bench.check_regression_floor(model, floor * 0.97, _ROOT)
            assert err is not None and "REGRESSION" in err, model
            assert model in err

    def test_unknown_model_or_missing_file_is_silent(self, bench, tmp_path):
        assert bench.check_regression_floor("nosuch", 1.0, _ROOT) is None
        assert bench.check_regression_floor(
            "resnet50", 1.0, str(tmp_path)) is None
