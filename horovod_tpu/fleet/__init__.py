"""hvtpu.fleet — multi-job resource arbiter over one elastic pool.

Gang scheduling (full min-world allocations only), priority preemption
through the graceful-drain channel (planned resizes, zero lost steps,
no restart-budget strikes), traffic-driven autoscaling hooks, and a
production front door: indexed journal intake with backpressure
(:mod:`.intake`), per-tenant quotas + weighted fair share + the
starvation guard (:mod:`.admission`), and topology-aware placement on
a virtual host torus (:mod:`.placement`).  See docs/fleet.md.
"""

from .admission import (AdmissionController, TenantConfigError,
                        TenantPolicy)
from .arbiter import FleetArbiter
from .autoscale import Autoscaler, FileSignal
from .intake import QueueFullError, SubmitJournal
from .job import (DONE, DRAINING, FAILED, FleetSpecError, Job, JobSpec,
                  PENDING, RESIZING, RUNNING, STATES, prefixed_client)
from .placement import PlacementPolicy, TorusGrid
from .runner import AllocationDiscovery, ElasticJobRunner

__all__ = [
    "FleetArbiter",
    "AdmissionController",
    "TenantConfigError",
    "TenantPolicy",
    "SubmitJournal",
    "QueueFullError",
    "PlacementPolicy",
    "TorusGrid",
    "Autoscaler",
    "FileSignal",
    "FleetSpecError",
    "Job",
    "JobSpec",
    "prefixed_client",
    "AllocationDiscovery",
    "ElasticJobRunner",
    "STATES",
    "PENDING",
    "RUNNING",
    "DRAINING",
    "RESIZING",
    "DONE",
    "FAILED",
]
