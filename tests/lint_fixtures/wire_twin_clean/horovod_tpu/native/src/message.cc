// Minimal fixture twin of native/src/message.cc (wire-twin clean case).
#include "message.h"

namespace hvt {

static void WriteEntry(Writer& w, const Entry& e) {
  w.u64(e.seq);
  w.str(e.name);
  w.u8(static_cast<uint8_t>(e.dtype));
}

std::vector<uint8_t> SerializeRequestList(const RequestList& rl) {
  Writer w;
  w.u32(kRequestMagic);
  w.u32(kWireVersion);
  w.i32(rl.rank);
  w.u8(rl.joined ? 1 : 0);
  w.u8(rl.shutdown ? 1 : 0);
  w.u8(rl.cache_bypass ? 1 : 0);
  w.u32(rl.burst_id);
  w.u32(rl.burst_len);
  for (const Request& rq : rl.requests) {
    WriteEntry(w, rq.entry);
  }
  return std::move(w.buf);
}

std::vector<uint8_t> SerializeResponseList(const ResponseList& rl) {
  Writer w;
  w.u32(kResponseMagic);
  w.u32(kWireVersion);
  w.u8(rl.shutdown ? 1 : 0);
  w.str(rl.error);
  return std::move(w.buf);
}

}  // namespace hvt
