"""core/retry.py: the bounded-backoff engine, named policies, and the
resilient coordination-KV wrapper (fault sites kv.get / kv.put)."""

import threading

import pytest

from horovod_tpu.core import faults, retry
from horovod_tpu.obs import metrics as obs_metrics


class Flaky:
    """Callable failing the first N calls with a given exception."""

    def __init__(self, fails, exc):
        self.fails = fails
        self.exc = exc
        self.calls = 0

    def __call__(self, *a):
        self.calls += 1
        if self.calls <= self.fails:
            raise self.exc
        return "ok"


def _fast(attempts=4, retryable=lambda e: True, **kw):
    return retry.RetryPolicy(name="t", max_attempts=attempts,
                             base_delay_s=0.0, retryable=retryable, **kw)


class TestCall:
    def test_succeeds_after_transient_failures(self):
        fn = Flaky(2, TimeoutError("x"))
        assert retry.call(_fast(), fn) == "ok"
        assert fn.calls == 3

    def test_exhaustion_reraises_original_error(self):
        fn = Flaky(10, TimeoutError("boom"))
        with pytest.raises(TimeoutError, match="boom"):
            retry.call(_fast(attempts=3), fn)
        assert fn.calls == 3

    def test_non_retryable_raises_immediately(self):
        fn = Flaky(10, ValueError("nope"))
        policy = _fast(retryable=lambda e: isinstance(e, TimeoutError))
        with pytest.raises(ValueError):
            retry.call(policy, fn)
        assert fn.calls == 1

    def test_deadline_bounds_the_loop(self):
        import time

        fn = Flaky(10**6, TimeoutError("x"))
        policy = retry.RetryPolicy(
            name="t", max_attempts=10**6, base_delay_s=0.01,
            max_delay_s=0.01, deadline_s=0.1, retryable=lambda e: True)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            retry.call(policy, fn)
        assert time.monotonic() - t0 < 2.0

    def test_result_based_retry(self):
        seen = []

        def fn():
            seen.append(1)
            return len(seen)

        policy = retry.RetryPolicy(
            name="t", max_attempts=5, base_delay_s=0.0,
            retry_result=lambda r: r < 3)
        assert retry.call(policy, fn) == 3

    def test_result_retry_returns_final_value_on_exhaustion(self):
        policy = retry.RetryPolicy(
            name="t", max_attempts=2, base_delay_s=0.0,
            retry_result=lambda r: True)
        assert retry.call(policy, lambda: "still-bad") == "still-bad"

    def test_on_retry_callback_counts(self):
        hits = []
        fn = Flaky(2, TimeoutError("x"))
        retry.call(_fast(), fn,
                   on_retry=lambda attempt, exc: hits.append(attempt))
        assert hits == [1, 2]

    def test_backoff_is_capped_full_jitter(self):
        import random

        policy = retry.RetryPolicy(name="t", max_attempts=10,
                                   base_delay_s=0.1, max_delay_s=0.5)
        rng = random.Random(7)
        for attempt in range(1, 10):
            s = policy.backoff_s(attempt, rng)
            assert 0.0 <= s <= 0.5

    def test_decorator_form(self):
        calls = []

        @retry.retrying(_fast())
        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise TimeoutError("x")
            return 42

        assert fn() == 42


class TestPolicies:
    def test_kv_retryable_classification(self):
        assert retry.kv_retryable(TimeoutError("t"))
        assert retry.kv_retryable(RuntimeError("UNAVAILABLE: conn"))
        assert retry.kv_retryable(RuntimeError("DEADLINE_EXCEEDED"))
        # a missing key is an ANSWER, not a transient failure
        assert not retry.kv_retryable(KeyError("NOT_FOUND: k"))
        assert not retry.kv_retryable(ValueError("bad arg"))
        # the blocking-get variant polls through NOT_FOUND
        assert retry.kv_blocking_retryable(RuntimeError("NOT_FOUND: k"))

    def test_kv_policy_env_knobs(self, monkeypatch):
        monkeypatch.setenv("HVTPU_KV_RETRY_ATTEMPTS", "7")
        monkeypatch.setenv("HVTPU_KV_RETRY_BASE_MS", "10")
        p = retry.kv_policy()
        assert p.max_attempts == 7
        assert p.base_delay_s == pytest.approx(0.01)

    def test_gloo_policy_markers(self):
        assert retry.is_gloo_infra_error("x Connection closed by peer y")
        assert retry.is_gloo_infra_error("collective transport failure")
        assert not retry.is_gloo_infra_error("assert 1 == 2")
        assert retry.GLOO_TEARDOWN.max_attempts == 5
        # injected faults say UNAVAILABLE — an infra retry must NOT
        # swallow them (they are the thing under test in chaos runs)
        assert not retry.is_gloo_infra_error("UNAVAILABLE (hvtpu "
                                             "injected fault: ...)")


class FlakyKV:
    """Coordination-client fake whose ops fail transiently N times."""

    def __init__(self, fails=0):
        self.d = {}
        self.fails = fails
        self.lock = threading.Lock()

    def _maybe_fail(self):
        with self.lock:
            if self.fails > 0:
                self.fails -= 1
                raise RuntimeError("UNAVAILABLE: coordinator blip")

    def key_value_set(self, k, v):
        self._maybe_fail()
        self.d[k] = v

    def key_value_try_get(self, k):
        self._maybe_fail()
        if k not in self.d:
            raise KeyError(f"NOT_FOUND: {k}")
        return self.d[k]

    def key_value_dir_get(self, prefix):
        self._maybe_fail()
        return [(k, v) for k, v in self.d.items()
                if k.startswith(prefix)]

    def key_value_delete(self, k):
        self.d.pop(k, None)


class TestResilientKV:
    def _kv(self, fails=0):
        fake = FlakyKV(fails)
        policy = retry.RetryPolicy(name="kv-test", max_attempts=4,
                                   base_delay_s=0.0,
                                   retryable=retry.kv_retryable)
        return fake, retry.ResilientKV(fake, rank=0, policy=policy)

    def test_put_survives_transient_unavailable(self):
        fake, kv = self._kv(fails=2)
        before = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retries_total").value()
        kv.key_value_set("a", "1")
        assert fake.d == {"a": "1"}
        after = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retries_total").value()
        assert after - before == 2

    def test_exhaustion_counts_and_reraises(self):
        fake, kv = self._kv(fails=50)
        before = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retry_exhausted_total").value()
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            kv.key_value_set("a", "1")
        after = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retry_exhausted_total").value()
        assert after - before == 1

    def test_miss_is_not_retried(self):
        fake, kv = self._kv()
        with pytest.raises(KeyError):
            kv.key_value_try_get("missing")

    def test_dir_get_presence_mirrors_client(self):
        fake, kv = self._kv()
        assert getattr(kv, "key_value_dir_get", None) is not None
        kv.key_value_set("p/x", "1")
        assert kv.key_value_dir_get("p/") == [("p/x", "1")]

        class NoDir:
            def key_value_set(self, k, v):
                pass

        bare = retry.ResilientKV(NoDir())
        # comm/stall.py picks strict mode off this exact probe
        assert getattr(bare, "key_value_dir_get", None) is None

    def test_idempotent_wrap(self):
        fake, kv = self._kv()
        assert retry.resilient_kv(kv) is kv
        assert retry.resilient_kv(None) is None

    def test_injected_drop_semantics(self):
        fake, kv = self._kv()
        faults.install("kv.put:drop@count=1,times=1; "
                       "kv.get:drop@count=1,times=1", rank=0)
        try:
            kv.key_value_set("a", "1")       # dropped
            assert fake.d == {}
            fake.d["b"] = "2"
            with pytest.raises(KeyError):    # dropped read = miss
                kv.key_value_try_get("b")
            # budgets spent: subsequent ops flow normally
            kv.key_value_set("c", "3")
            assert fake.d["c"] == "3"
            assert kv.key_value_try_get("b") == "2"
        finally:
            faults.uninstall()

    def test_injected_error_is_retried_to_success(self):
        """An error-injected KV op carries the UNAVAILABLE marker, so
        the retry policy heals it — the self-healing loop end to end."""
        fake, kv = self._kv()
        faults.install("kv.put:error@count=1,times=1", rank=0)
        try:
            kv.key_value_set("a", "1")
            assert fake.d == {"a": "1"}
        finally:
            faults.uninstall()
