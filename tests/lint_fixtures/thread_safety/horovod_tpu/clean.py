"""thread-safety fixture: correct lock discipline the pass must accept."""

import threading


class CleanWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []          # hvtpulint: guarded-by(_lock)
        self._depth = 0           # hvtpulint: guarded-by(_lock, racy-read-ok)
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        while True:
            with self._lock:
                self._drain()

    def _drain(self):             # hvtpulint: requires(_lock)
        while self._queue:
            self._queue.pop()

    def submit(self, item):
        with self._lock:
            self._queue.append(item)
            self._depth += 1

    def peek_depth(self):
        # Fine: racy-read-ok read without the lock.
        return self._depth

    def _unreachable_helper(self):
        # Private and never called from an entry point — not checked.
        self._queue.clear()
