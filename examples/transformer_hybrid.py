"""Hybrid-parallel transformer training: dp × pp × tp with sequence
parallelism riding the tp axis and expert parallelism riding dp —
the post-parity parallel layer (SURVEY.md §2.7 extensions; the
reference is data-parallel only).

Runs on any device count: the mesh factorization adapts.  On this
sandbox: 8 virtual CPU devices (default below) or the real TPU chip
(drop the --cpu-devices flag on a pod slice).

Run:  python examples/transformer_hybrid.py --cpu-devices 8
"""

import argparse
import os
import sys

# source-checkout convenience: this example is run directly (no
# launcher to inject PYTHONPATH), so make the repo root importable
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu-devices", type=int, default=8,
                   help="0 = use the default platform (e.g. real TPU)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--layers", type=int, default=4)
    args = p.parse_args()

    import jax

    if args.cpu_devices:
        from horovod_tpu.core.state import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvt
    import horovod_tpu.parallel as par
    from horovod_tpu.models.transformer import (
        TransformerConfig,
        init_params as transformer_init_params,
        make_train_step as transformer_train_step,
    )

    hvt.init()
    devices = jax.devices()
    n = len(devices)
    if n % 4 == 0:
        dp, pp, tp = n // 4, 2, 2
    elif n % 2 == 0:
        dp, pp, tp = n // 2, 1, 2
    else:
        dp, pp, tp = n, 1, 1
    layout = par.make_layout(devices, dp=dp, tp=tp, pp=pp)
    print(f"mesh: dp={dp} pp={pp} tp={tp} over {n} devices "
          f"(sp rides tp, ep rides dp)")

    cfg = TransformerConfig(
        vocab_size=256, d_model=args.d_model, n_heads=4,
        n_layers=args.layers, d_ff=args.d_model * 4, max_seq=64,
        dtype=jnp.float32, n_experts=2 * max(1, dp),
        num_microbatches=2,
    )
    params = transformer_init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(3e-3)
    step = transformer_train_step(cfg, layout, tx)
    opt_state = tx.init(params)

    rng = np.random.RandomState(0)
    toks = jnp.asarray(
        rng.randint(0, 256, size=(4 * max(2, dp), 33)), jnp.int32
    )
    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, toks)
        losses.append(float(loss))
        print(f"step {i}: loss={losses[-1]:.4f}", flush=True)
    assert losses[-1] < losses[0], "loss must decrease on a fixed batch"
    print("hybrid-parallel training OK")


if __name__ == "__main__":
    main()
