"""TensorFlow frontend: the ``horovod.tensorflow``-compatible surface
on the TPU engine.

Parity surface: ``horovod/tensorflow/__init__.py`` —
``hvd.init/rank/size``, eager+graph collectives (mpi_ops.py here),
``DistributedGradientTape``, ``DistributedOptimizer``,
``broadcast_variables``, object helpers, ``Compression``.  A tf.keras
user switches with only the import line changed
(``import horovod.tensorflow as hvd`` →
``import horovod_tpu.tensorflow as hvd``).
"""

from __future__ import annotations

import tensorflow as tf

import horovod_tpu as _hvt

# ---- lifecycle / topology (shared engine state) ----
init = _hvt.init
shutdown = _hvt.shutdown
is_initialized = _hvt.is_initialized
rank = _hvt.rank
size = _hvt.size
local_rank = _hvt.local_rank
local_size = _hvt.local_size
cross_rank = _hvt.cross_rank
cross_size = _hvt.cross_size
mpi_enabled = _hvt.mpi_enabled
mpi_built = _hvt.mpi_built
mpi_threads_supported = _hvt.mpi_threads_supported
gloo_enabled = _hvt.gloo_enabled
gloo_built = _hvt.gloo_built
nccl_built = _hvt.nccl_built
ddl_built = _hvt.ddl_built
ccl_built = _hvt.ccl_built
cuda_built = _hvt.cuda_built
rocm_built = _hvt.rocm_built
xla_built = _hvt.xla_built
start_timeline = _hvt.start_timeline
stop_timeline = _hvt.stop_timeline
ProcessSet = _hvt.ProcessSet
add_process_set = _hvt.add_process_set
remove_process_set = _hvt.remove_process_set
HorovodInternalError = _hvt.core.exceptions.HorovodInternalError
HostsUpdatedInterrupt = _hvt.core.exceptions.HostsUpdatedInterrupt

from .compression import Compression  # noqa: E402
from . import mpi_ops  # noqa: E402
from .mpi_ops import (  # noqa: E402
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    grouped_allgather,
    grouped_allreduce,
    grouped_reducescatter,
    join,
    reducescatter,
)
from . import elastic  # noqa: E402
from .sync_batch_norm import SyncBatchNormalization  # noqa: E402


# ---------------------------------------------------------------------------
# variable / object helpers
# ---------------------------------------------------------------------------

is_homogeneous = _hvt.is_homogeneous


def size_op(process_set_id: int = 0, name=None):
    """Graph-usable size of the given process set (parity:
    hvd.size_op).  The value is fixed for the life of the (static)
    job, so a constant tensor is the faithful TPU-native lowering."""
    if process_set_id == 0:
        n = size()
    else:
        st = _hvt.core.state.require_init("size_op")
        n = st.process_set_table.get(process_set_id).size
    return tf.constant(n, tf.int32, name=name or "horovod_size")


def rank_op(name=None):
    """Graph-usable rank (parity: hvd.rank_op)."""
    return tf.constant(rank(), tf.int32, name=name or "horovod_rank")


def local_rank_op(name=None):
    """Graph-usable local rank (parity: hvd.local_rank_op)."""
    return tf.constant(local_rank(), tf.int32,
                       name=name or "horovod_local_rank")


def local_size_op(name=None):
    """Graph-usable local size (parity: hvd.local_size_op)."""
    return tf.constant(local_size(), tf.int32,
                       name=name or "horovod_local_size")


def broadcast_variables(variables, root_rank: int = 0, process_set=None):
    """Assign every variable its root-rank value (parity:
    hvd.broadcast_variables).

    All variables ride ONE fused byte buffer: the native thread pool
    packs the host values in parallel, a single broadcast moves the
    bytes, and each variable is assigned its slice (the same
    FusionBufferManager-style fast path as the torch frontend's
    broadcast_parameters).
    """
    import numpy as np

    variables = [v for v in variables if v is not None]
    if not variables:
        return
    if len(variables) == 1 or not tf.executing_eagerly():
        # TF1 session callers run the returned grouped op; tf.function
        # callers execute the assigns as traced side effects
        return _broadcast_variables_graph(variables, root_rank,
                                          process_set)
    from ..comm import eager as _eager_comm
    from ..comm.packing import pack_bytes, unpack_bytes

    raws = [v.numpy() for v in variables]
    buf, specs = pack_bytes(raws)
    out = np.asarray(_eager_comm.broadcast(
        buf, root_rank=root_rank, process_set=process_set
    ))
    for var, piece in zip(variables, unpack_bytes(out, specs)):
        var.assign(piece)


def _broadcast_variables_graph(variables, root_rank, process_set):
    """Trace-compatible fused broadcast: inside tf.function the host-
    numpy pack is unavailable, so fusion happens IN-GRAPH — variables
    are grouped by dtype, each group concatenated into one flat tensor,
    broadcast once (one engine round-trip per dtype instead of one per
    variable — N py_function hops at graph-mode startup was the
    measured cost), then split and assigned back.  Variables with
    dynamic shapes fall back to per-variable broadcasts.  Returns one
    grouped op so a TF1 session caller can ``session.run`` it
    (tf.function callers execute the assigns as traced side effects)."""
    by_dtype = {}
    singles = []
    assigns = []
    for v in variables:
        if v.shape.is_fully_defined():
            by_dtype.setdefault(v.dtype.base_dtype, []).append(v)
        else:
            singles.append(v)
    for dtype, vs in by_dtype.items():
        if len(vs) == 1:
            singles.extend(vs)
            continue
        sizes = [int(v.shape.num_elements()) for v in vs]
        fused = tf.concat(
            [tf.reshape(tf.convert_to_tensor(v), [-1]) for v in vs], 0
        )
        out = broadcast(fused, root_rank=root_rank,
                        process_set=process_set)
        # py_function erases static shape; restore for split
        out = tf.ensure_shape(out, [sum(sizes)])
        for v, part in zip(vs, tf.split(out, sizes)):
            assigns.append(v.assign(tf.reshape(part, v.shape)))
    for v in singles:
        assigns.append(v.assign(
            broadcast(tf.convert_to_tensor(v), root_rank=root_rank,
                      process_set=process_set)
        ))
    return tf.group(*assigns)


def broadcast_global_variables(root_rank: int = 0):
    """TF1 parity: ``hvd.broadcast_global_variables(root_rank)`` — an
    op assigning every variable in the v1 GLOBAL_VARIABLES collection
    its root-rank value; run it once after session creation."""
    if tf.executing_eagerly():
        raise RuntimeError(
            "broadcast_global_variables() is graph-mode only (the "
            "global-variables collection is a TF1 concept); use "
            "broadcast_variables(model.variables, root_rank) eagerly")
    return _broadcast_variables_graph(
        tf.compat.v1.global_variables(), root_rank, None)


class BroadcastGlobalVariablesHook(tf.compat.v1.train.SessionRunHook):
    """TF1 parity: ``hvd.BroadcastGlobalVariablesHook(0)`` — a
    SessionRunHook for ``tf.compat.v1.train.MonitoredTrainingSession``
    / tf.estimator that broadcasts rank 0's initial global variables
    once the session exists (the reference's canonical way to start
    v1 ranks from identical weights)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        # accepted for signature parity; placement is engine-side
        self.device = device
        self.bcast_op = None

    def begin(self):
        self.bcast_op = broadcast_global_variables(self.root_rank)

    def after_create_session(self, session, coord):
        session.run(self.bcast_op)


def broadcast_object(obj, root_rank: int = 0, process_set=None):
    from ..api import functions as _functions

    return _functions.broadcast_object(obj, root_rank=root_rank,
                                       process_set=process_set)


def broadcast_object_fn(root_rank: int = 0, session=None, name=None,
                        process_set=None):
    """Parity: hvd.broadcast_object_fn — returns a callable
    ``bcast(obj)`` bound to the given root (``session`` and ``name``
    accepted for reference signature compatibility; the engine
    broadcast is session-free and self-naming)."""
    def _bcast(obj):
        return broadcast_object(obj, root_rank=root_rank,
                                process_set=process_set)

    return _bcast


def allgather_object(obj, process_set=None):
    from ..api import functions as _functions

    return _functions.allgather_object(obj, process_set=process_set)


# ---------------------------------------------------------------------------
# DistributedGradientTape (the TF2 training idiom)
# ---------------------------------------------------------------------------

class _DistributedGradientTape:
    """Parity: hvd.DistributedGradientTape — tape whose ``gradient()``
    allreduces every gradient before returning it.

    A delegating proxy rather than a tf.GradientTape subclass: the
    real tape's internals (the pywrap tape handle) stay untouched, so
    ``watch``/``jacobian``/context-manager use all behave exactly like
    the wrapped tape.  (``isinstance(dtape, tf.GradientTape)`` is
    False — same trade the reference's wrapper effectively makes by
    rebuilding tape internals per TF version.)
    """

    def __init__(self, tape: tf.GradientTape, device_dense="",
                 device_sparse="", compression=Compression.none,
                 sparse_as_dense=False, op=Average,
                 gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0, process_set=None):
        self.__dict__["_tape"] = tape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op
        self._predivide = gradient_predivide_factor
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self.__dict__["_tape"], item)

    def __enter__(self):
        self.__dict__["_tape"].__enter__()
        return self

    def __exit__(self, *exc):
        return self.__dict__["_tape"].__exit__(*exc)

    def _allreduce_one(self, grad):
        if grad is None:
            return None
        if isinstance(grad, tf.IndexedSlices) and self._sparse_as_dense:
            grad = tf.convert_to_tensor(grad)
        op, prescale, postscale = mpi_ops.predivide_scaling(
            self._op, self._predivide, self._process_set
        )
        return allreduce(
            grad, op=op, compression=self._compression,
            prescale_factor=prescale, postscale_factor=postscale,
            process_set=self._process_set,
        )

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        grads = self.__dict__["_tape"].gradient(
            target, sources, output_gradients, **kwargs
        )
        # sources may be an arbitrary nest (list/tuple/dict); allreduce
        # every leaf (None leaves pass through)
        return tf.nest.map_structure(self._allreduce_one, grads)


def DistributedGradientTape(gradtape, device_dense="", device_sparse="",
                            compression=Compression.none,
                            sparse_as_dense=False, op=Average,
                            gradient_predivide_factor: float = 1.0,
                            num_groups: int = 0, process_set=None):
    """Parity: hvd.DistributedGradientTape(tape)."""
    return _DistributedGradientTape(
        gradtape, device_dense, device_sparse, compression,
        sparse_as_dense, op, gradient_predivide_factor, num_groups,
        process_set,
    )


# ---------------------------------------------------------------------------
# DistributedOptimizer
# ---------------------------------------------------------------------------

def DistributedOptimizer(optimizer, name=None, device_dense="",
                         device_sparse="", compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         gradient_predivide_factor: float = 1.0,
                         backward_passes_per_step: int = 1,
                         average_aggregated_gradients: bool = True,
                         num_groups: int = 0, process_set=None):
    """Wrap an optimizer so gradients are allreduced before being
    applied (parity: hvd.DistributedOptimizer for TF).

    Keras (2 or 3) optimizers are wrapped via the dynamic-subclass
    trick of horovod/_keras/__init__.py (create_distributed_optimizer);
    tf.compat.v1 optimizers get their ``compute_gradients`` wrapped.
    """
    import keras as _keras_pkg

    if isinstance(optimizer, _keras_pkg.optimizers.Optimizer):
        from .._keras import create_distributed_optimizer

        return create_distributed_optimizer(
            optimizer, name=name, compression=compression, op=op,
            gradient_predivide_factor=gradient_predivide_factor,
            backward_passes_per_step=backward_passes_per_step,
            average_aggregated_gradients=average_aggregated_gradients,
            process_set=process_set,
        )
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _LegacyDistributedOptimizer(
            optimizer, compression=compression, op=op,
            process_set=process_set,
        )
    raise ValueError(
        f"unsupported optimizer type {type(optimizer)!r}; expected a "
        "keras optimizer or tf.compat.v1.train.Optimizer"
    )


class _LegacyDistributedOptimizer(tf.compat.v1.train.Optimizer):
    """compute_gradients-wrapping path (parity: the v1 optimizer wrap
    in horovod/tensorflow/__init__.py)."""

    def __init__(self, optimizer, compression=Compression.none,
                 op=Average, process_set=None):
        self._optimizer = optimizer
        self._compression = compression
        self._op = op
        self._process_set = process_set
        super().__init__(name="HvtpuDistributed", use_locking=False)

    def compute_gradients(self, *args, **kwargs):
        gradvars = self._optimizer.compute_gradients(*args, **kwargs)
        return [
            (
                allreduce(g, op=self._op, compression=self._compression,
                          process_set=self._process_set)
                if g is not None else None,
                v,
            )
            for g, v in gradvars
        ]

    def apply_gradients(self, *args, **kwargs):
        return self._optimizer.apply_gradients(*args, **kwargs)

    def get_slot(self, *args, **kwargs):
        return self._optimizer.get_slot(*args, **kwargs)

    def get_slot_names(self, *args, **kwargs):
        return self._optimizer.get_slot_names(*args, **kwargs)

    def variables(self, *args, **kwargs):
        return self._optimizer.variables(*args, **kwargs)


__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size",
    "mpi_enabled", "mpi_built", "mpi_threads_supported", "gloo_enabled",
    "gloo_built", "nccl_built", "ddl_built", "ccl_built", "cuda_built",
    "rocm_built", "xla_built",
    "start_timeline", "stop_timeline",
    "ProcessSet", "add_process_set", "remove_process_set",
    "HorovodInternalError", "HostsUpdatedInterrupt",
    "Sum", "Average", "Adasum", "Min", "Max", "Product",
    "allreduce", "grouped_allreduce", "allgather", "grouped_allgather",
    "broadcast", "alltoall", "reducescatter", "grouped_reducescatter",
    "barrier", "join", "elastic", "SyncBatchNormalization",
    "broadcast_variables", "broadcast_global_variables",
    "BroadcastGlobalVariablesHook", "broadcast_object",
    "broadcast_object_fn", "allgather_object",
    "is_homogeneous", "size_op", "rank_op", "local_rank_op",
    "local_size_op",
    "Compression", "DistributedGradientTape", "DistributedOptimizer",
]


def __getattr__(name: str):
    # forward the live module attribute (parity: per-frontend
    # hvd.global_process_set); AttributeError keeps hasattr contracts
    if name == "global_process_set":
        return getattr(_hvt, "global_process_set")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
