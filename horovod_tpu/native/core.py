"""ctypes bindings to libhvt_core.so (built on demand from native/src).

Parity surface: ``horovod/common/basics.py`` (``HorovodBasics`` loading
the native lib via ctypes) + the enqueue path of
``horovod/torch/mpi_ops_v2.cc``.  The library is compiled lazily with
``make`` the first time it is needed (the reference compiles at pip
install time; a source build at first import is the equivalent for a
pure-source checkout).  When no toolchain is available, callers fall
back to :mod:`horovod_tpu.native.fallback`, which implements the same
protocol in Python.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import List, Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libhvt_core.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def build(force: bool = False) -> Optional[str]:
    """Compile libhvt_core.so with make/g++; returns its path or None."""
    with _build_lock:
        if os.environ.get("HVTPU_SKIP_NATIVE_BUILD"):
            return _LIB_PATH if os.path.exists(_LIB_PATH) else None
        # Always invoke make: its dependency tracking makes this a no-op
        # when the .so is current, and picks up edits to src/*.cc that a
        # bare existence check would silently ignore.
        if force:
            subprocess.run(["make", "-C", _HERE, "-s", "clean"],
                           capture_output=True)
        try:
            subprocess.run(
                ["make", "-C", _HERE, "-s"],
                check=True,
                capture_output=True,
                timeout=300,
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return _LIB_PATH if os.path.exists(_LIB_PATH) else None
        return _LIB_PATH if os.path.exists(_LIB_PATH) else None


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.hvt_abi_version.restype = c.c_int
    lib.hvt_controller_new.restype = c.c_void_p
    lib.hvt_controller_new.argtypes = [
        c.c_int, c.c_int, c.c_int64, c.c_int64, c.c_double, c.c_double,
    ]
    lib.hvt_controller_free.argtypes = [c.c_void_p]
    lib.hvt_controller_enqueue.restype = c.c_int
    lib.hvt_controller_enqueue.argtypes = [
        c.c_void_p, c.c_uint64, c.c_char_p, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_int64), c.c_int, c.c_int, c.c_int64, c.c_int,
    ]
    lib.hvt_controller_declare_group.argtypes = [c.c_void_p, c.c_int64, c.c_int]
    lib.hvt_controller_register_process_set.argtypes = [
        c.c_void_p, c.c_int, c.POINTER(c.c_int32), c.c_int,
    ]
    lib.hvt_controller_set_joined.argtypes = [c.c_void_p]
    lib.hvt_controller_set_tuned.argtypes = [
        c.c_void_p, c.c_int64, c.c_int32
    ]
    lib.hvt_controller_set_shutdown.argtypes = [c.c_void_p]
    lib.hvt_controller_set_resync_every.argtypes = [c.c_void_p, c.c_int64]
    lib.hvt_controller_force_resync.argtypes = [c.c_void_p]
    lib.hvt_controller_predict_responses.restype = c.c_int64
    lib.hvt_controller_predict_responses.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint32), c.c_int64,
        c.POINTER(c.c_uint8), c.c_int64,
    ]
    lib.hvt_controller_finish_names.restype = c.c_int64
    lib.hvt_controller_finish_names.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64,
        c.POINTER(c.c_uint64), c.c_int64,
    ]
    lib.hvt_controller_drain_requests.restype = c.c_int64
    lib.hvt_controller_drain_requests.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint8), c.c_int64, c.c_int64,
    ]
    lib.hvt_controller_ingest.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint8), c.c_int64,
    ]
    lib.hvt_controller_compute_responses.restype = c.c_int64
    lib.hvt_controller_compute_responses.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint8), c.c_int64,
    ]
    lib.hvt_controller_apply_responses.restype = c.c_int64
    lib.hvt_controller_apply_responses.argtypes = [
        c.c_void_p, c.POINTER(c.c_uint8), c.c_int64,
        c.POINTER(c.c_uint64), c.c_int64,
    ]
    lib.hvt_controller_pending_count.restype = c.c_int64
    lib.hvt_controller_pending_count.argtypes = [c.c_void_p]
    lib.hvt_controller_pending_bytes.restype = c.c_int64
    lib.hvt_controller_pending_bytes.argtypes = [c.c_void_p]
    lib.hvt_controller_cache_size.restype = c.c_int64
    lib.hvt_controller_cache_size.argtypes = [c.c_void_p]
    lib.hvt_controller_set_fusion_threshold.argtypes = [c.c_void_p, c.c_int64]
    lib.hvt_controller_check_stalls.restype = c.c_int64
    lib.hvt_controller_check_stalls.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64,
    ]
    lib.hvt_parallel_gather.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_int64), c.c_int64,
    ]
    lib.hvt_parallel_scatter.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.POINTER(c.c_uint8)),
        c.POINTER(c.c_int64), c.c_int64,
    ]
    lib.hvt_pool_num_threads.restype = c.c_int
    lib.hvt_timeline_new.restype = c.c_void_p
    lib.hvt_timeline_new.argtypes = [c.c_char_p, c.c_int]
    lib.hvt_timeline_free.argtypes = [c.c_void_p]
    lib.hvt_timeline_event.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char, c.c_char_p, c.c_double, c.c_double,
    ]
    lib.hvt_timeline_mark_cycle.argtypes = [c.c_void_p, c.c_double]
    lib.hvt_timeline_flush.argtypes = [c.c_void_p]
    lib.hvt_gp_predict.restype = c.c_int
    lib.hvt_gp_predict.argtypes = [
        c.POINTER(c.c_double), c.POINTER(c.c_double), c.c_int64, c.c_int64,
        c.POINTER(c.c_double), c.c_int64, c.c_double, c.c_double,
        c.c_double, c.POINTER(c.c_double), c.POINTER(c.c_double),
    ]
    lib.hvt_gp_expected_improvement.restype = c.c_int
    lib.hvt_gp_expected_improvement.argtypes = [
        c.POINTER(c.c_double), c.POINTER(c.c_double), c.c_int64, c.c_int64,
        c.POINTER(c.c_double), c.c_int64, c.c_double, c.c_double,
        c.c_double, c.c_double, c.c_double, c.POINTER(c.c_double),
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _lib_tried
    if _lib is not None:
        return _lib
    if _lib_tried:
        return None
    _lib_tried = True
    path = build()
    if path is None:
        return None
    try:
        # AttributeError covers a stale .so missing newer symbols (the
        # ABI check below would reject it too, but only if _configure
        # survives) — fall back to the Python twin either way.
        _lib = _configure(ctypes.CDLL(path))
    except (OSError, AttributeError):
        return None
    if _lib.hvt_abi_version() != 5:
        _lib = None
    return _lib


def available() -> bool:
    return load() is not None


def _as_u8(buf: bytearray) -> "ctypes.POINTER(ctypes.c_uint8)":
    return (ctypes.c_uint8 * len(buf)).from_buffer(buf)


class NativeController:
    """Thin OO wrapper over the C controller (see fallback.PyController
    for the Python twin with identical semantics)."""

    def __init__(self, rank: int, size: int, fusion_threshold: int,
                 cache_capacity: int = 1024, stall_warn_s: float = 60.0,
                 stall_abort_s: float = 0.0, resync_every: int = 64):
        lib = load()
        if lib is None:
            raise RuntimeError("native core unavailable; use fallback")
        self._lib = lib
        self._ptr = lib.hvt_controller_new(
            rank, size, fusion_threshold, cache_capacity,
            stall_warn_s, stall_abort_s,
        )
        self.rank = rank
        self.size = size
        self.fusion_threshold = fusion_threshold
        self.resync_every = resync_every
        if resync_every != 64:
            lib.hvt_controller_set_resync_every(self._ptr, resync_every)

    def close(self):
        if self._ptr:
            self._lib.hvt_controller_free(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def enqueue(self, seq: int, name: str, op_type: int, red_op: int,
                dtype: int, shape: Sequence[int], process_set_id: int = 0,
                group_id: int = -1, root_rank: int = -1) -> bool:
        arr = (ctypes.c_int64 * len(shape))(*shape)
        rc = self._lib.hvt_controller_enqueue(
            self._ptr, seq, name.encode(), op_type, red_op, dtype,
            arr, len(shape), process_set_id, group_id, root_rank,
        )
        return rc == 0

    def declare_group(self, group_id: int, size: int):
        self._lib.hvt_controller_declare_group(self._ptr, group_id, size)

    def register_process_set(self, psid: int, ranks: Sequence[int]):
        arr = (ctypes.c_int32 * len(ranks))(*ranks)
        self._lib.hvt_controller_register_process_set(
            self._ptr, psid, arr, len(ranks)
        )

    def set_joined(self):
        self._lib.hvt_controller_set_joined(self._ptr)

    def _blob_call(self, fn) -> bytes:
        n = fn(self._ptr, None, 0)
        if n == 0:
            return b""
        buf = bytearray(n)
        fn(self._ptr, _as_u8(buf), n)
        return bytes(buf)

    def drain_requests(self, limit: int = 0) -> bytes:
        """limit > 0 caps the drained entries at the caller's known
        steady burst size (atomic-burst cap; 0 = drain everything)."""
        fn = self._lib.hvt_controller_drain_requests
        n = fn(self._ptr, None, 0, limit)
        if n == 0:
            return b""
        buf = bytearray(n)
        fn(self._ptr, _as_u8(buf), n, limit)
        return bytes(buf)

    def ingest(self, blob: bytes):
        buf = bytearray(blob)
        self._lib.hvt_controller_ingest(self._ptr, _as_u8(buf), len(blob))

    def compute_responses(self) -> bytes:
        return self._blob_call(self._lib.hvt_controller_compute_responses)

    def apply_responses(self, blob: bytes, max_finished: int = 65536
                        ) -> List[int]:
        buf = bytearray(blob)
        out = (ctypes.c_uint64 * max_finished)()
        n = self._lib.hvt_controller_apply_responses(
            self._ptr, _as_u8(buf), len(blob), out, max_finished
        )
        return list(out[: min(n, max_finished)])

    @property
    def pending_count(self) -> int:
        return self._lib.hvt_controller_pending_count(self._ptr)

    @property
    def pending_bytes(self) -> int:
        return self._lib.hvt_controller_pending_bytes(self._ptr)

    @property
    def cache_size(self) -> int:
        return self._lib.hvt_controller_cache_size(self._ptr)

    def set_fusion_threshold(self, nbytes: int):
        self.fusion_threshold = nbytes
        self._lib.hvt_controller_set_fusion_threshold(self._ptr, nbytes)

    def set_tuned(self, fusion_threshold: int, cycle_time_us: int):
        """Publish autotuned params in subsequent ResponseLists
        (coordinator only; parity: ParameterManager broadcast)."""
        self._lib.hvt_controller_set_tuned(
            self._ptr, fusion_threshold, cycle_time_us
        )

    def set_shutdown(self):
        """Announce this rank wants to shut down (next DrainRequests)."""
        self._lib.hvt_controller_set_shutdown(self._ptr)

    def set_resync_every(self, n: int):
        """Bypass cadence: every Nth all-cache-hit cycle sends a full
        resync blob (0 disables the bypass fast path entirely)."""
        self.resync_every = int(n)
        self._lib.hvt_controller_set_resync_every(self._ptr, int(n))

    def force_resync(self):
        """Rank-side re-anchor (mispredict recovery / quiesce rollback):
        the next drain_requests emits a full-entry resync frame exactly
        as if the coordinator had requested cache_resync_needed."""
        self._lib.hvt_controller_force_resync(self._ptr)

    def predict_responses(self, bits: Sequence[int]) -> Optional[bytes]:
        """Predicted steady-state ResponseList for a pure bypass cycle
        of exactly ``bits`` (see fallback.PyController); None when a
        bit is unknown."""
        arr = (ctypes.c_uint32 * len(bits))(*bits)
        n = self._lib.hvt_controller_predict_responses(
            self._ptr, arr, len(bits), None, 0)
        if n == 0:
            return None
        buf = bytearray(n)
        self._lib.hvt_controller_predict_responses(
            self._ptr, arr, len(bits), _as_u8(buf), n)
        return bytes(buf)

    def finish(self, names: Sequence[str],
               max_finished: int = 65536) -> List[int]:
        """Eagerly retire predicted-executed in-flight entries."""
        joined = "\n".join(names).encode()
        out = (ctypes.c_uint64 * max_finished)()
        n = self._lib.hvt_controller_finish_names(
            self._ptr, joined, len(joined), out, max_finished)
        return list(out[: min(n, max_finished)])

    def check_stalls(self) -> List[dict]:
        n = int(self._lib.hvt_controller_check_stalls(self._ptr, None, 0))
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.hvt_controller_check_stalls(self._ptr, buf, n + 1)
        return json.loads(buf.raw[:n].decode())


class NativeTimeline:
    """Chrome-trace writer backed by native/src/timeline.cc."""

    def __init__(self, path: str, rank: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._ptr = lib.hvt_timeline_new(path.encode(), rank)
        if not self._ptr:
            raise OSError(f"cannot open timeline file: {path}")

    def event(self, name: str, ph: str, category: str, ts_us: float,
              dur_us: float = 0.0):
        self._lib.hvt_timeline_event(
            self._ptr, name.encode(), ph.encode(), category.encode(),
            ts_us, dur_us,
        )

    def mark_cycle(self, ts_us: float):
        self._lib.hvt_timeline_mark_cycle(self._ptr, ts_us)

    def flush(self):
        self._lib.hvt_timeline_flush(self._ptr)

    def close(self):
        if self._ptr:
            self._lib.hvt_timeline_free(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def parallel_gather(dst: memoryview, srcs: List[memoryview]) -> None:
    """Pack many buffers into one flat staging buffer using the native
    thread pool (parity: MemcpyInFusionBuffer + thread_pool.cc)."""
    lib = load()
    n = len(srcs)
    if n == 0:
        return
    sizes = (ctypes.c_int64 * n)(*[len(s) for s in srcs])
    if lib is None:
        off = 0
        for s in srcs:
            dst[off:off + len(s)] = s
            off += len(s)
        return
    dst_arr = (ctypes.c_uint8 * len(dst)).from_buffer(dst)
    src_ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)()
    keep = []
    for i, s in enumerate(srcs):
        a = (ctypes.c_uint8 * len(s)).from_buffer(s if not s.readonly
                                                  else bytearray(s))
        keep.append(a)
        src_ptrs[i] = ctypes.cast(a, ctypes.POINTER(ctypes.c_uint8))
    lib.hvt_parallel_gather(dst_arr, src_ptrs, sizes, n)


def parallel_scatter(src: memoryview, dsts: List[memoryview]) -> None:
    """Unpack one flat buffer into many (parity: MemcpyOutFusionBuffer)."""
    lib = load()
    n = len(dsts)
    if n == 0:
        return
    sizes = (ctypes.c_int64 * n)(*[len(d) for d in dsts])
    if lib is None:
        off = 0
        for d in dsts:
            d[:] = src[off:off + len(d)]
            off += len(d)
        return
    src_buf = bytearray(src) if src.readonly else src
    src_arr = (ctypes.c_uint8 * len(src)).from_buffer(src_buf)
    dst_ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)()
    keep = []
    for i, d in enumerate(dsts):
        a = (ctypes.c_uint8 * len(d)).from_buffer(d)
        keep.append(a)
        dst_ptrs[i] = ctypes.cast(a, ctypes.POINTER(ctypes.c_uint8))
    lib.hvt_parallel_scatter(src_arr, dst_ptrs, sizes, n)


def _as_c_doubles(arr):
    import numpy as np

    a = np.ascontiguousarray(arr, dtype=np.float64)
    return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def gp_predict(xs, ys, cand, *, length_scale: float, noise: float,
               signal_variance: float):
    """Native GP posterior (mu, sigma) at ``cand`` (parity:
    gaussian_process.cc GaussianProcessRegressor).  Returns None when
    the native lib is unavailable or the Gram matrix is singular — the
    caller falls back to the numpy twin."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    xs_np, xs_p = _as_c_doubles(np.atleast_2d(xs))
    ys_np, ys_p = _as_c_doubles(np.asarray(ys).reshape(-1))
    cand_np, cand_p = _as_c_doubles(np.atleast_2d(cand))
    n, d = xs_np.shape
    m = cand_np.shape[0]
    # shape discipline before raw pointers cross the C boundary: a
    # mismatch would stride wrongly (silent garbage) or read OOB; the
    # numpy twin raises, so raise here too
    if cand_np.shape[1] != d or ys_np.shape[0] != n:
        raise ValueError(
            f"gp_predict shape mismatch: xs {xs_np.shape}, "
            f"ys {ys_np.shape}, cand {cand_np.shape}"
        )
    mu = np.empty(m, np.float64)
    sigma = np.empty(m, np.float64)
    rc = lib.hvt_gp_predict(
        xs_p, ys_p, n, d, cand_p, m,
        ctypes.c_double(length_scale), ctypes.c_double(noise),
        ctypes.c_double(signal_variance),
        mu.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        sigma.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        return None
    return mu, sigma


def gp_expected_improvement(xs, ys, cand, *, length_scale: float,
                            noise: float, signal_variance: float,
                            best_y: float, xi: float):
    """Native fit+predict+EI in one call (parity: the EI loop of
    bayesian_optimization.cc NextSample).  None -> caller falls back."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    xs_np, xs_p = _as_c_doubles(np.atleast_2d(xs))
    ys_np, ys_p = _as_c_doubles(np.asarray(ys).reshape(-1))
    cand_np, cand_p = _as_c_doubles(np.atleast_2d(cand))
    n, d = xs_np.shape
    m = cand_np.shape[0]
    if cand_np.shape[1] != d or ys_np.shape[0] != n:
        raise ValueError(
            f"gp_expected_improvement shape mismatch: xs {xs_np.shape}, "
            f"ys {ys_np.shape}, cand {cand_np.shape}"
        )
    ei = np.empty(m, np.float64)
    rc = lib.hvt_gp_expected_improvement(
        xs_p, ys_p, n, d, cand_p, m,
        ctypes.c_double(length_scale), ctypes.c_double(noise),
        ctypes.c_double(signal_variance), ctypes.c_double(best_y),
        ctypes.c_double(xi),
        ei.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        return None
    return ei
