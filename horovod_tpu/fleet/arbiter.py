"""The multi-job resource arbiter: one pool, N jobs, gang scheduling,
priority preemption via graceful drain, autoscaling hooks.

The arbiter promotes the one-job ElasticDriver into a fleet: it owns a
:class:`~horovod_tpu.elastic.discovery.HostManager` over the POOL's
discovery (the same cooldown-blacklist machinery the single-job driver
uses) and divides the discovered slots among jobs.  Everything is
driven by :meth:`tick` — a pure, lock-held scheduling pass over
arbiter state — so the production loop (:meth:`run`, real clock
thread), the CLI server, tier-1 fake-clock tests, and the fabric
simulator (a kernel task calling ``tick()`` on virtual time) all run
the SAME logic.

Scheduling policy (deterministic by construction):

- **Gang scheduling.**  A job launches only when its full ``min_np``
  allocation is free — never a partial gang.  Pending jobs are visited
  in (priority desc, submit order) order; a small job behind a starved
  big one may backfill (no slot is held idle waiting), because the big
  one acquires its gang through preemption, not accumulation.
- **Start-time expansion.**  When every pending job has been placed,
  freshly-started jobs widen toward ``max_np`` with the leftover slots
  (free — the job has not launched yet).  Already-RUNNING jobs never
  auto-expand; growth is the autoscaler's (or an operator's) call,
  because a grow costs the job a commit-boundary reset.
- **Priority preemption.**  A pending job that cannot fit may reclaim
  slots from strictly-lower-priority RUNNING jobs, shrinking each
  victim toward its ``min_np`` — never evicting below it.  Victim
  order is lowest priority first, and within a tier the YOUNGEST job
  (highest submit_seq) yields first; ``submit_seq`` is unique, so
  selection is a total order (the tie-break determinism tests pin
  this).  The shrink rides the planned-drain channel: per-rank
  ``core/preempt.py`` notice files → coordinated emergency commit →
  ``DRAIN_EXIT_CODE`` exits → a resize with zero lost steps and no
  restart-budget or blacklist strike.  If the drain grace expires, the
  arbiter escalates (SIGTERM) and the victim pays a charged restart.
- **Fail fast.**  A pending job whose ``min_np`` exceeds the pool's
  total discovered capacity can never run; it FAILs immediately with a
  diagnostic naming both numbers.

Thread safety: ``_lock`` guards all arbiter state; ``tick``/``submit``
/``cancel``/``debug_state`` take it.  Job handles have their own
internal locks and never call back into the arbiter.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Callable, Dict, List, Optional

from ..core import clock
from ..elastic.discovery import HostManager
from ..obs import metrics as obs_metrics
from . import admission as admission_mod
from . import intake as intake_mod
from . import job as job_mod
from .autoscale import Autoscaler
from .job import (DONE, DRAINING, FAILED, FleetSpecError, Job, JobSpec,
                  PENDING, RESIZING, RUNNING, STATES)
from .placement import PlacementPolicy

__all__ = ["FleetArbiter"]

_M_JOBS = obs_metrics.gauge(
    "hvtpu_fleet_jobs",
    "Fleet jobs per lifecycle state (label: state).")
_M_SLOTS_TOTAL = obs_metrics.gauge(
    "hvtpu_fleet_pool_slots_total",
    "Schedulable slots in the fleet pool (discovered minus "
    "blacklist-cooldown hosts).")
_M_SLOTS_USED = obs_metrics.gauge(
    "hvtpu_fleet_pool_slots_used",
    "Pool slots currently allocated to live jobs.")
_M_PREEMPTIONS = obs_metrics.counter(
    "hvtpu_fleet_preemptions_total",
    "Planned shrinks the arbiter issued on lower-priority jobs "
    "(priority preemption + autoscale shrinks), via the graceful-"
    "drain channel.")
_M_QUEUE_WAIT = obs_metrics.histogram(
    "hvtpu_fleet_queue_wait_seconds",
    "Submit-to-launch wait per job: how long the gang waited for its "
    "full min-world allocation.")
_M_RESIZE_S = obs_metrics.histogram(
    "hvtpu_fleet_resize_seconds",
    "Arbiter-initiated resize latency: shrink request to the victim "
    "running again at its new size.")
_M_AUTOSCALE = obs_metrics.counter(
    "hvtpu_fleet_autoscale_events_total",
    "Autoscale decisions applied (label: direction = grow | shrink).")
_M_JOB_STEP_RATE = obs_metrics.gauge(
    "hvtpu_fleet_job_step_rate",
    "Per-job EWMA optimizer steps/second from the latest fleet health "
    "summary (label: job; 0 until the job publishes).")
_M_JOB_INCIDENTS = obs_metrics.gauge(
    "hvtpu_fleet_job_incidents",
    "Per-job total anomaly incidents from the latest fleet health "
    "summary (label: job).")
_M_QUEUE_DEPTH = obs_metrics.gauge(
    "hvtpu_fleet_queue_depth",
    "PENDING jobs per priority tier (label: tier).")
_M_JOB_STALL_AGE = obs_metrics.gauge(
    "hvtpu_fleet_job_stall_age_seconds",
    "Per-job stall age from the latest fleet health summary: seconds "
    "since the last completed step while a newer stall warning is "
    "outstanding; 0 when healthy (label: job).")


class FleetArbiter:
    """One shared pool serving N prioritised elastic jobs."""

    def __init__(self, discovery, *,
                 fleet_dir: Optional[str] = None,
                 tick_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 runner_factory: Optional[Callable[[Job], object]] = None,
                 event_fn: Optional[Callable[..., None]] = None,
                 blacklist_cooldown: Optional[float] = None,
                 verbose: bool = False,
                 register_debug: bool = True,
                 health_client=None):
        self.hosts = HostManager(discovery,
                                 cooldown_base_s=blacklist_cooldown)
        if fleet_dir is None:
            fleet_dir = os.environ.get("HVTPU_FLEET_DIR")
        self.fleet_dir = fleet_dir
        if tick_s is None:
            tick_s = float(
                os.environ.get("HVTPU_FLEET_TICK_SECONDS", "1") or 1)
        self.tick_s = tick_s
        if drain_grace_s is None:
            drain_grace_s = float(
                os.environ.get("HVTPU_FLEET_DRAIN_GRACE_SECONDS", "30")
                or 30)
        self.drain_grace_s = drain_grace_s
        self._event_fn = event_fn
        self.verbose = verbose
        if runner_factory is None:
            base = (os.path.join(fleet_dir, "jobs") if fleet_dir
                    else tempfile.mkdtemp(prefix="hvtpu_fleet_"))

            def runner_factory(j, _base=base):
                from .runner import ElasticJobRunner

                return ElasticJobRunner(j, _base, verbose=self.verbose)

        self._runner_factory = runner_factory
        # Optional KV client reaching the jobs' prefixed health keys
        # (fleet/health.py): each tick pulls fleet/<job>/health and
        # folds it into state.json + the per-job fleet gauges.
        self._health_client = health_client
        self._lock = threading.RLock()
        self.jobs: Dict[str, Job] = {}  # hvtpulint: guarded-by(_lock)
        self._autoscalers: Dict[str, Autoscaler] = {}  # hvtpulint: guarded-by(_lock)
        self._submit_seq = 0  # hvtpulint: guarded-by(_lock)
        self._pool_seen = False  # hvtpulint: guarded-by(_lock)
        # front door: indexed intake + admission + placement (all
        # touched only under _lock — see their module docstrings)
        self._journal = (intake_mod.SubmitJournal(fleet_dir)
                         if fleet_dir else None)
        self._intake_budget = intake_mod.intake_budget()
        self._admission = admission_mod.AdmissionController(fleet_dir)
        self._placement = PlacementPolicy()
        self._depth_tiers: set = set()  # hvtpulint: guarded-by(_lock)
        self._stop = threading.Event()
        self._registered_debug = register_debug
        if register_debug:
            obs_metrics.register_debug_provider("fleet", self.debug_state)

    # -- events ---------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self._event_fn is not None:
            self._event_fn(f"fleet.{kind}", **fields)
        if self.verbose:
            detail = " ".join(f"{k}={v}" for k, v in fields.items())
            print(f"hvtpu.fleet: {kind} {detail}", flush=True)

    # -- submit / cancel -------------------------------------------------
    def submit(self, spec: JobSpec) -> Job:
        """Queue a validated spec; duplicate live names are rejected
        (the name keys the state dir and KV prefix)."""
        with self._lock:
            return self._submit_locked(spec)

    def _submit_locked(self, spec: JobSpec) -> Job:  # hvtpulint: requires(_lock)
        existing = self.jobs.get(spec.name)
        if existing is not None and not existing.terminal:
            raise FleetSpecError(
                "name", f"job {spec.name!r} already exists "
                f"(state {existing.state})")
        self._submit_seq += 1
        job = Job(spec, self._submit_seq)
        self.jobs[spec.name] = job
        if spec.autoscale is not None:
            asc = Autoscaler.from_spec(spec.autoscale)
            if asc is not None:
                self._autoscalers[spec.name] = asc
            else:
                self._event("autoscale_unconfigured", job=spec.name)
        self._event("submit", job=spec.name, priority=spec.priority,
                    min_np=spec.min_np, max_np=spec.max_np)
        return job

    def attach_autoscaler(self, name: str, autoscaler: Autoscaler
                          ) -> None:
        with self._lock:
            if name not in self.jobs:
                raise KeyError(f"unknown job {name!r}")
            self._autoscalers[name] = autoscaler

    def cancel(self, name: str) -> bool:
        with self._lock:
            return self._cancel_locked(name)

    def _cancel_locked(self, name: str) -> bool:  # hvtpulint: requires(_lock)
        job = self.jobs.get(name)
        if job is None or job.terminal:
            return False
        job.cancelled = True
        if job.state == PENDING:
            job.to(FAILED, reason="cancelled")
        elif job.handle is not None:
            job.handle.stop()  # whole-job graceful drain
        self._event("cancel", job=name, state=job.state)
        return True

    # -- the scheduling pass ---------------------------------------------
    def tick(self) -> None:
        """One full arbiter pass: journal+spool intake → pool refresh
        → reap → fail-fast → gang schedule (+preempt) → autoscale →
        publish → journal-cursor commit (after state.json persists)."""
        with self._lock:
            # reload tenants BEFORE intake: queued-quota checks on the
            # first post-(re)start tick must see the current table, or
            # a journal backlog slips past admission un-quota'd
            self._reload_tenants()
            self._intake_journal()
            self._intake_spool()
            self._refresh_pool()
            self._reap()
            self._fail_oversized()
            self._schedule()
            self._autoscale_tick()
            self._poll_health()
            self._publish()
            # cursor commit LAST: _publish wrote state.json, so the
            # intaken batch is durable before its records are skipped
            self._commit_journal()

    def _refresh_pool(self) -> None:  # hvtpulint: requires(_lock)
        try:
            self.hosts.refresh()
        except Exception as e:  # noqa: BLE001 — transient discovery failure
            self._event("discovery_error", error=str(e)[:200])
            return
        if self.hosts.last_found:
            self._pool_seen = True

    def _live_jobs(self) -> List[Job]:  # hvtpulint: requires(_lock)
        return [j for j in self.jobs.values() if not j.terminal]

    def _free_map(self) -> Dict[str, int]:  # hvtpulint: requires(_lock)
        """host → unallocated schedulable slots (negative clamped: a
        pool that shrank below its allocations frees nothing)."""
        free = dict(self.hosts.current)
        for j in self._live_jobs():
            for h, n in j.allocation.items():
                if h in free:
                    free[h] -= n
        return {h: n for h, n in free.items() if n > 0}

    def _tenant_used(self) -> Dict[str, int]:  # hvtpulint: requires(_lock)
        """tenant → currently allocated ranks across its live jobs
        (PENDING jobs contribute their tenant key at 0 use)."""
        used: Dict[str, int] = {}
        for j in self._live_jobs():
            t = j.spec.tenant_key
            used[t] = used.get(t, 0) + sum(j.allocation.values())
        return used

    def _reap(self) -> None:  # hvtpulint: requires(_lock)
        """Adopt every handle's view: exits, phase changes, live
        allocations, charged restarts, drain-grace escalation."""
        now = clock.monotonic()
        for j in self._live_jobs():
            h = j.handle
            if h is None:
                continue
            j.charged_restarts = j.restarts_base + h.charged_restarts
            code = h.poll()
            if code is not None:
                j.exit_code = code
                j.allocation = {}
                if j.cancelled:
                    j.to(FAILED, reason="cancelled")
                elif code == 0:
                    j.to(DONE)
                else:
                    j.to(FAILED, reason=f"exit {code}")
                self._event("job_end", job=j.name, state=j.state,
                            code=code,
                            charged_restarts=j.charged_restarts)
                continue
            phase = h.phase()
            if j.state == DRAINING:
                if phase == "resizing":
                    j.to(RESIZING)
                elif phase == "running" and h.target_np() is None:
                    # drain landed and the relaunch won the race with
                    # this tick
                    self._finish_resize(j, now)
                elif (j.shrink_deadline is not None
                      and now >= j.shrink_deadline
                      and not j.shrink_escalated):
                    j.shrink_escalated = True
                    n = h.escalate()
                    self._event("drain_grace_expired", job=j.name,
                                signalled=n)
            elif j.state == RESIZING and phase == "running":
                self._finish_resize(j, now)
            elif j.state == RUNNING and phase == "resizing":
                # an external event (spot reclaim drain, crash) is
                # resizing the job without the arbiter asking
                j.to(RESIZING)
            j.allocation = h.allocation()

    def _finish_resize(self, j: Job, now: float) -> None:
        j.to(RUNNING)
        if j.shrink_started_t is not None:
            _M_RESIZE_S.observe(now - j.shrink_started_t)
            self._event("resized", job=j.name,
                        np=j.handle.current_np(),
                        resize_s=round(now - j.shrink_started_t, 6))
        j.shrink_started_t = None
        j.shrink_deadline = None
        j.shrink_escalated = False

    def _fail_oversized(self) -> None:  # hvtpulint: requires(_lock)
        """A gang that can NEVER fit (min_np > the pool's total
        discovered capacity) fails fast with both numbers named."""
        if not self._pool_seen:
            return
        capacity = sum(self.hosts.last_found.values())
        for j in self._live_jobs():
            if j.state == PENDING and j.spec.min_np > capacity:
                j.to(FAILED, reason=(
                    f"min_np={j.spec.min_np} can never fit: the pool "
                    f"has {capacity} total slots"))
                self._event("job_unschedulable_fatal", job=j.name,
                            min_np=j.spec.min_np, capacity=capacity)

    def _reload_tenants(self) -> None:  # hvtpulint: requires(_lock)
        note = self._admission.maybe_reload()
        if note == "reloaded":
            self._event("tenants_reload")
        elif note:
            self._event("tenants_rejected", error=note[:300])

    def _schedule(self) -> None:  # hvtpulint: requires(_lock)
        """Gang schedule the pending queue in admission order: aged
        (starvation-guarded) jobs first, then priority tiers, same-tier
        ties broken by the tenant FURTHEST BELOW its weighted fair
        share, then submit order.  Quota-deferred jobs (tenant at its
        max_ranks cap) park without blocking anyone else."""
        now = clock.monotonic()
        pending = [j for j in self.jobs.values() if j.state == PENDING]
        if not pending:
            return
        used_by_tenant = self._tenant_used()
        slots_total = sum(self.hosts.current.values())
        deficits = self._admission.deficits(used_by_tenant, slots_total)
        age_s = admission_mod.starvation_s()
        aged = set()
        for j in pending:
            if age_s > 0 and now - j.submit_t >= age_s:
                aged.add(j.name)
                if not j.aged_reported:
                    j.aged_reported = True
                    self._event("job_aged", job=j.name,
                                priority=j.spec.priority,
                                waited_s=round(now - j.submit_t, 3))
        order = sorted(pending, key=lambda j: (
            j.name not in aged, -j.spec.priority,
            -deficits.get(j.spec.tenant_key, 0.0), j.submit_seq))
        free = self._free_map()
        min_running_pri = min(
            (v.spec.priority for v in self.jobs.values()
             if v.state == RUNNING and v.handle is not None),
            default=None)
        started: List[Job] = []
        all_placed = True
        for j in order:
            t = j.spec.tenant_key
            quota_msg = self._admission.check_start(
                t, used_by_tenant.get(t, 0), j.spec.min_np)
            if quota_msg is not None:
                if not j.quota_reported:
                    j.quota_reported = True
                    self._event("quota_wait", job=j.name, tenant=t,
                                detail=quota_msg)
                continue  # deferred by policy, not by capacity
            total = sum(free.values())
            if total >= j.spec.min_np:
                alloc = self._placement.carve(
                    free, j.spec.min_np, self.hosts.current)
                self._start_job(j, alloc)
                j.quota_reported = False
                used_by_tenant[t] = (used_by_tenant.get(t, 0)
                                     + sum(alloc.values()))
                started.append(j)
            else:
                all_placed = False
                boosted = j.name in aged
                # preemption can only help when SOME running job sits
                # below this job's (effective) tier — cheap filter so
                # a deep queue never pays O(running) per waiter
                if min_running_pri is not None and (
                        boosted
                        or min_running_pri < j.spec.priority):
                    self._maybe_preempt(j, total, boosted=boosted)
                elif not j.unschedulable_reported:
                    j.unschedulable_reported = True
                    self._event("job_waiting", job=j.name,
                                min_np=j.spec.min_np, free=total,
                                missing=j.spec.min_np - total)
        # start-time expansion: only when nothing is left waiting
        if all_placed:
            for j in sorted(started,
                            key=lambda j: (-j.spec.priority,
                                           j.submit_seq)):
                self._expand_at_start(j, free, used_by_tenant)
        # launch AFTER expansion so each gang starts once, full-width
        for j in started:
            j.handle.start(j.allocation)
            self._event("job_start", job=j.name,
                        np=sum(j.allocation.values()),
                        queue_wait_s=round(j.queue_wait_s or 0.0, 6))

    def _start_job(self, j: Job, alloc: Dict[str, int]) -> None:
        j.allocation = alloc
        j.handle = self._runner_factory(j)
        j.to(RUNNING)
        if j.queue_wait_s is not None:
            _M_QUEUE_WAIT.observe(j.queue_wait_s)

    def _expand_at_start(self, j: Job, free: Dict[str, int],
                         used_by_tenant: Dict[str, int]
                         ) -> None:  # hvtpulint: requires(_lock)
        total = sum(free.values())
        cur = sum(j.allocation.values())
        cap = j.spec.max_np if j.spec.max_np is not None else cur + total
        t = j.spec.tenant_key
        p = self._admission.policy(t)
        if p.max_ranks is not None:
            # the tenant's quota caps growth too (its current use
            # already includes this job's gang)
            cap = min(cap, cur + max(
                0, p.max_ranks - used_by_tenant.get(t, 0)))
        extra = min(cap - cur, total)
        if extra <= 0:
            return
        more = self._placement.carve(free, extra, self.hosts.current,
                                     near=j.allocation)
        for h, n in more.items():
            j.allocation[h] = j.allocation.get(h, 0) + n
        used_by_tenant[t] = (used_by_tenant.get(t, 0)
                             + sum(more.values()))

    def _maybe_preempt(self, j: Job, free_total: int, *,
                       boosted: bool = False
                       ) -> None:  # hvtpulint: requires(_lock)
        """Reclaim ``min_np - free`` slots from strictly-lower-priority
        RUNNING jobs, shrinking each toward its min.  Victim order:
        priority asc, then YOUNGEST first (submit_seq desc) — a unique
        total order.  A ``boosted`` (starvation-aged) job outranks
        every tier, so its wait is bounded by the aging threshold plus
        one drain cycle."""
        need = j.spec.min_np - free_total
        victims = sorted(
            (v for v in self.jobs.values()
             if v.state == RUNNING and v.handle is not None
             and (boosted or v.spec.priority < j.spec.priority)),
            key=lambda v: (v.spec.priority, -v.submit_seq))
        plan = []
        for v in victims:
            if need <= 0:
                break
            cur = sum(v.allocation.values())
            reclaim = min(cur - v.spec.min_np, need)
            if reclaim > 0:
                plan.append((v, cur - reclaim))
                need -= reclaim
        if need > 0:
            if not j.unschedulable_reported:
                j.unschedulable_reported = True
                self._event("job_waiting", job=j.name,
                            min_np=j.spec.min_np, free=free_total,
                            missing=need)
            return
        j.unschedulable_reported = False
        for v, new_np in plan:
            self._start_shrink(v, new_np,
                               reason=f"preempted for {j.name}")

    def _start_shrink(self, v: Job, new_np: int, reason: str) -> None:
        if not v.handle.request_shrink(new_np):
            return  # between incarnations; retried next tick
        now = clock.monotonic()
        v.preemptions += 1
        v.shrink_started_t = now
        v.shrink_deadline = now + self.drain_grace_s
        v.shrink_escalated = False
        v.to(DRAINING, reason=reason)
        _M_PREEMPTIONS.inc()
        self._event("preempt", victim=v.name, to_np=new_np,
                    reason=reason)

    def _autoscale_tick(self) -> None:  # hvtpulint: requires(_lock)
        now = clock.monotonic()
        for name in sorted(self._autoscalers):
            asc = self._autoscalers[name]
            j = self.jobs.get(name)
            if j is None or j.state != RUNNING or j.handle is None:
                continue
            decision = asc.evaluate(now)
            if decision is None:
                continue
            direction, step = decision
            cur = sum(j.allocation.values())
            if direction == "grow":
                free = self._free_map()
                cap = (j.spec.max_np if j.spec.max_np is not None
                       else cur + sum(free.values()))
                pol = self._admission.policy(j.spec.tenant_key)
                if pol.max_ranks is not None:
                    used = self._tenant_used().get(
                        j.spec.tenant_key, 0)
                    cap = min(cap, cur + max(0, pol.max_ranks - used))
                extra = min(step, cap - cur, sum(free.values()))
                if extra <= 0:
                    continue
                more = self._placement.carve(
                    free, extra, self.hosts.current,
                    near=j.allocation)
                alloc = dict(j.allocation)
                for h, n in more.items():
                    alloc[h] = alloc.get(h, 0) + n
                j.allocation = alloc
                j.handle.update_allocation(alloc)
                _M_AUTOSCALE.inc(direction="grow")
                self._event("autoscale", job=name, direction="grow",
                            np=sum(alloc.values()),
                            signal=asc.last_signal)
            else:
                new_np = max(j.spec.min_np, cur - step)
                if new_np >= cur:
                    continue
                _M_AUTOSCALE.inc(direction="shrink")
                self._event("autoscale", job=name, direction="shrink",
                            np=new_np, signal=asc.last_signal)
                self._start_shrink(j, new_np, reason="autoscale")

    # -- crash recovery ---------------------------------------------------
    def recover(self) -> int:
        """Resume from a previous arbiter incarnation's ``state.json``:
        every non-terminal job is resubmitted as PENDING with its
        restart/preemption accounting restored.  Worker processes were
        children of the dead arbiter, so there is nothing to adopt —
        the next tick gang-launches each recovered job afresh and its
        elastic state dir (the durable commit plane) makes the resume
        exact.  Terminal jobs stay forgotten (their record lives in
        the event log).  Returns the number of jobs recovered; a
        missing or unreadable state.json recovers nothing."""
        d = self.fleet_dir
        if not d:
            return 0
        try:
            with open(os.path.join(d, "state.json")) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return 0
        recovered = 0
        with self._lock:
            for row in state.get("jobs", []):
                if not isinstance(row, dict) or row.get("state") in (
                        DONE, FAILED):
                    continue
                spec_d = row.get("spec")
                if not isinstance(spec_d, dict):
                    # a pre-spec state.json (older arbiter): the job
                    # cannot be reconstructed — surface, don't guess
                    self._event("recover_skipped",
                                job=str(row.get("name")),
                                error="state.json row carries no spec")
                    continue
                try:
                    spec = JobSpec.from_dict(spec_d)
                except FleetSpecError as e:
                    self._event("recover_skipped",
                                job=str(row.get("name")),
                                error=str(e)[:300])
                    continue
                existing = self.jobs.get(spec.name)
                if existing is not None and not existing.terminal:
                    continue  # already resubmitted (idempotent recover)
                job = self._submit_locked(spec)
                try:
                    job.preemptions = int(row.get("preemptions") or 0)
                    job.restarts_base = int(
                        row.get("charged_restarts") or 0)
                    job.charged_restarts = job.restarts_base
                except (TypeError, ValueError):
                    pass
                recovered += 1
                self._event("recover", job=job.name,
                            prior_state=row.get("state"))
        return recovered

    # -- indexed intake (journal ↔ arbiter) ------------------------------
    def _intake_journal(self) -> None:  # hvtpulint: requires(_lock)
        """Apply at most ``intake_budget`` journal records in seq
        order.  The cursor is NOT committed here: that happens in
        :meth:`_commit_journal` at the end of the tick, after
        ``state.json`` has persisted the admitted jobs — a crash
        anywhere in between replays the batch (replayed submits dedupe
        against their live job) instead of losing submissions the CLI
        already acknowledged.  Cancels ordered after their submit in
        the journal can also tombstone a record still sitting in the
        LEGACY spool dir, so a cancelled job never surfaces as
        PENDING."""
        jr = self._journal
        if jr is None:
            return
        batch = jr.read_batch(self._intake_budget)
        for rec in batch:
            op = rec.get("op")
            if op == "submit":
                self._apply_journal_submit(rec)
            elif op == "cancel":
                name = str(rec.get("name") or "")
                if not self._cancel_locked(name):
                    self._tombstone_spooled(name)
            else:
                admission_mod.M_REJECTS.inc(reason="corrupt_record")
                self._event("journal_corrupt",
                            seq=int(rec.get("seq") or 0))

    def _commit_journal(self) -> None:  # hvtpulint: requires(_lock)
        """Commit the journal cursor — only called AFTER the jobs
        admitted this tick are durable in ``state.json``.  Ordering
        matters: committing first would open a window where a crash
        loses acknowledged submissions (advanced cursor skips their
        records, state.json never saw them).  The reverse window —
        state persisted, cursor not yet committed — merely replays the
        batch, which ``_apply_journal_submit`` dedupes."""
        if self._journal is not None:
            self._journal.commit(budget=self._intake_budget,
                                 tick_s=self.tick_s)

    def _apply_journal_submit(self, rec: dict) -> None:  # hvtpulint: requires(_lock)
        seq = int(rec.get("seq") or 0)
        try:
            spec = JobSpec.from_dict(rec.get("spec"))
        except FleetSpecError as e:
            admission_mod.M_REJECTS.inc(reason="spec_invalid")
            self._reject(f"journal-{seq}", str(e))
            return
        existing = self.jobs.get(spec.name)
        if existing is not None and not existing.terminal:
            if existing.spec.to_dict() == spec.to_dict():
                # replay of an already-applied record (crash between
                # apply and cursor commit, or recover() raced it):
                # consume silently — exactly-once at the job level
                self._event("journal_duplicate", job=spec.name,
                            seq=seq)
            else:
                admission_mod.M_REJECTS.inc(reason="duplicate_name")
                self._reject(
                    f"journal-{seq}",
                    f"field 'name': job {spec.name!r} already exists "
                    f"(state {existing.state})")
            return
        t = spec.tenant_key
        queued = sum(1 for j in self.jobs.values()
                     if j.state == PENDING
                     and j.spec.tenant_key == t)
        msg = self._admission.check_queued(t, queued)
        if msg is not None:
            admission_mod.M_REJECTS.inc(reason="tenant_queued_quota")
            self._reject(f"journal-{seq}", msg)
            return
        self._submit_locked(spec)

    def _tombstone_spooled(self, name: str) -> None:  # hvtpulint: requires(_lock)
        """A cancel for a job the arbiter has never seen: consume any
        matching legacy spool file so the job never goes PENDING."""
        if not self.fleet_dir or not name:
            return
        path = os.path.join(self.fleet_dir, "submit", f"{name}.json")
        try:
            os.unlink(path)
        except OSError:
            self._event("cancel_unknown", job=name)
            return
        self._event("cancel_spooled", job=name)

    # -- legacy spool protocol (file-per-submit CLI ↔ arbiter) -----------
    def _intake_spool(self) -> None:  # hvtpulint: requires(_lock)
        d = self.fleet_dir
        if not d:
            return
        # cancel markers FIRST: a marker must be able to tombstone a
        # same-tick spool file before that file becomes a PENDING job
        can = os.path.join(d, "cancel")
        if os.path.isdir(can):
            for fn in sorted(os.listdir(can)):
                if not self._cancel_locked(fn):
                    self._tombstone_spooled(fn)
                try:
                    os.unlink(os.path.join(can, fn))
                except OSError:
                    pass
        sub = os.path.join(d, "submit")
        if os.path.isdir(sub):
            for fn in sorted(os.listdir(sub)):
                if not fn.endswith(".json"):
                    continue
                path = os.path.join(sub, fn)
                try:
                    spec = JobSpec.load(path)
                except FleetSpecError as e:
                    self._reject(fn, str(e))
                else:
                    existing = self.jobs.get(spec.name)
                    if (existing is not None and not existing.terminal
                            and existing.spec.to_dict()
                            == spec.to_dict()):
                        # this exact submit already landed — an
                        # arbiter that crashed between intake and
                        # unlink (or recover() beat the spool to it).
                        # Consume the file instead of rejecting the
                        # live job's own spec as a duplicate.
                        self._event("spool_duplicate", spool=fn,
                                    job=spec.name)
                    else:
                        try:
                            self._submit_locked(spec)
                        except FleetSpecError as e:
                            self._reject(fn, str(e))
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def _reject(self, fn: str, message: str) -> None:
        self._event("submit_rejected", spool=fn, error=message[:300])
        d = os.path.join(self.fleet_dir, "rejected")
        try:
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, fn + ".error"), "w") as f:
                f.write(message + "\n")
        except OSError:
            pass

    def _poll_health(self) -> None:  # hvtpulint: requires(_lock)
        """Pull each live job's health summary (fleet/health.py) off
        the shared KV when one exists (the fabric simulator), else off
        the per-job health-file channel the ElasticJobRunner handle
        exposes as ``health_dir``; a missing/None read keeps the
        previous summary so one flaky tick doesn't blank the rollup."""
        from . import health as health_mod

        for j in self._live_jobs():
            summary = None
            if self._health_client is not None:
                summary = health_mod.read(self._health_client, j.name)
            if summary is None:
                hd = getattr(j.handle, "health_dir", None)
                if hd:
                    summary = health_mod.read_file(hd)
            if summary is not None:
                j.health = summary

    def _publish(self) -> None:  # hvtpulint: requires(_lock)
        counts = {s: 0 for s in STATES}
        for j in self.jobs.values():
            counts[j.state] += 1
        for s, c in counts.items():
            _M_JOBS.set(c, state=s)
        total = sum(self.hosts.current.values())
        used = sum(n for j in self._live_jobs()
                   for n in j.allocation.values())
        _M_SLOTS_TOTAL.set(total)
        _M_SLOTS_USED.set(min(used, total) if total else used)
        depth: Dict[int, int] = {}
        for j in self.jobs.values():
            if j.state == PENDING:
                depth[j.spec.priority] = depth.get(
                    j.spec.priority, 0) + 1
        self._depth_tiers |= set(depth)
        for tier in self._depth_tiers:  # zero emptied tiers, not stale
            _M_QUEUE_DEPTH.set(depth.get(tier, 0), tier=str(tier))
        self._placement.fragmentation(self._free_map(),
                                      self.hosts.current)
        for j in self._live_jobs():
            h = j.health
            if h:
                _M_JOB_STEP_RATE.set(
                    float(h.get("step_rate") or 0.0), job=j.name)
                _M_JOB_INCIDENTS.set(
                    float(h.get("incidents_total") or 0.0), job=j.name)
                _M_JOB_STALL_AGE.set(
                    float(h.get("stall_age_s") or 0.0), job=j.name)
        if self.fleet_dir:
            self._write_state_json()

    def _write_state_json(self) -> None:  # hvtpulint: requires(_lock)
        state = self.debug_state_locked()
        path = os.path.join(self.fleet_dir, "state.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, sort_keys=True, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass

    # -- read side -------------------------------------------------------
    def debug_state(self) -> dict:
        with self._lock:
            return self.debug_state_locked()

    def debug_state_locked(self) -> dict:  # hvtpulint: requires(_lock)
        free = self._free_map()
        out = {
            "t_wall": round(clock.wall(), 3),
            "pool": {
                "hosts": dict(self.hosts.current),
                "blacklisted": self.hosts.blacklisted_now(),
                "slots_total": sum(self.hosts.current.values()),
                "slots_free": sum(free.values()),
            },
            "jobs": [j.info()
                     for j in sorted(self.jobs.values(),
                                     key=lambda j: j.submit_seq)],
            "autoscalers": {n: a.debug_state()
                            for n, a in sorted(
                                self._autoscalers.items())},
            "admission": self._admission.debug_state(),
        }
        if self._journal is not None:
            out["intake"] = {"backlog": self._journal.depth()}
        return out

    def all_terminal(self) -> bool:
        with self._lock:
            return bool(self.jobs) and all(
                j.terminal for j in self.jobs.values())

    # -- loop ------------------------------------------------------------
    def run(self, until_idle: bool = False) -> None:
        """Tick on ``tick_s`` cadence (through the clock seam) until
        :meth:`stop` — or, with ``until_idle``, until every submitted
        job is terminal."""
        while not self._stop.is_set():
            self.tick()
            if until_idle and self.all_terminal():
                return
            clock.sleep(self.tick_s)

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        if self._registered_debug:
            try:
                obs_metrics.unregister_debug_provider("fleet")
            except Exception:  # noqa: BLE001 — already unregistered
                pass


# keep the job module import visible for re-exports (fleet/__init__)
_ = job_mod
