"""Exception types for horovod_tpu.

Parity surface: the reference's ``horovod/common/exceptions.py``
(``HorovodInternalError``, ``HostsUpdatedInterrupt``) — the two exception
types the elastic training loop catches to trigger state restore / re-init.
"""


class HorovodTpuError(Exception):
    """Base class for all horovod_tpu errors."""


class HorovodInternalError(HorovodTpuError):
    """A collective operation failed (device loss, comm failure, desync).

    Elastic training loops catch this, roll back to the last committed
    state, re-initialize, and continue (see ``horovod_tpu.elastic``).
    """


class HvtpuMismatchError(HorovodInternalError):
    """Ranks submitted conflicting metadata for the same tensor name.

    The coordinator detected that member ranks announced different
    (op type, reduction op, dtype, shape, root rank) for one tensor
    name — the cross-rank disagreement class that silently mis-fuses
    or hangs a collective stack.  The error text names each offending
    rank and what it submitted; every member rank raises it instead
    of stalling (parity: the reference controller's "Mismatched ..."
    error responses).
    """


class HvtpuDivergenceError(HorovodInternalError):
    """The parameter divergence audit found replicas that differ.

    Raised by ``core/audit.py`` under ``HVTPU_AUDIT_ACTION=abort``.
    Subclasses :class:`HorovodInternalError` so an elastic training
    loop rolls back to the last commit and the driver relaunches the
    world from verified-identical state.
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The set of participating hosts/slices changed (elastic membership).

    Raised at a commit boundary after the worker-notification service flags
    a membership change; the training loop re-initializes with the new
    world without rolling back state.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class DrainInterrupt(HostsUpdatedInterrupt):
    """A member rank is draining after a preemption notice
    (core/preempt.py); raised on the REMAINING ranks at the agreed
    drain-commit boundary.

    The drain commit already persisted this step, so the committed
    state stands — no rollback.  Subclasses
    :class:`HostsUpdatedInterrupt` so user training loops that catch
    the parent keep working unchanged; the elastic run wrapper catches
    this first to count the reset as ``peer_drain``.
    """

    def __init__(self, rank: int = -1):
        super().__init__(skip_sync=False)
        #: rank that announced the departure (-1 if unknown)
        self.rank = rank


class NotInitializedError(HorovodTpuError):
    """An API requiring ``horovod_tpu.init()`` was called before init."""

    def __init__(self, name: str = "operation"):
        super().__init__(
            f"horovod_tpu has not been initialized; call horovod_tpu.init() "
            f"before using {name}."
        )


class StallError(HorovodTpuError):
    """The stall inspector declared a rank permanently missing."""
