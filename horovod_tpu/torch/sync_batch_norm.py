"""Cross-rank synchronized BatchNorm (parity:
horovod/torch/sync_batch_norm.py ``SyncBatchNorm``).

Training-mode statistics are computed over the GLOBAL batch: local
(sum, sum-of-squares, count) are summed across ranks with one grouped
allreduce, and the backward pass allreduces the two reduction terms of
the batchnorm gradient — the same two-collective structure as the
reference's allgather-based implementation, expressed as sums (cheaper
on the wire, mathematically identical).
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

import horovod_tpu as _hvt

from . import mpi_ops


class SyncBatchNorm(_BatchNorm):
    """Drop-in for ``torch.nn.BatchNorm*d`` with cross-rank statistics."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True, process_set=None):
        super().__init__(num_features, eps, momentum, affine,
                         track_running_stats)
        self._process_set = process_set

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)"
            )

    def forward(self, input: torch.Tensor) -> torch.Tensor:
        self._check_input_dim(input)
        if not self.training or _hvt.size() == 1:
            # eval mode / single rank: vanilla batchnorm semantics
            return super().forward(input)
        # momentum=None is torch's cumulative-moving-average mode: the
        # effective factor is 1/num_batches_tracked.
        if self.track_running_stats and self.num_batches_tracked is not None:
            self.num_batches_tracked.add_(1)
            if self.momentum is None:
                factor = 1.0 / float(self.num_batches_tracked)
            else:
                factor = self.momentum
        else:
            factor = 0.0 if self.momentum is None else self.momentum
        return _SyncBatchNormFn.apply(
            input, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, factor, self._process_set,
        )


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, input, weight, bias, running_mean, running_var,
                eps, momentum, process_set):
        c = input.shape[1]
        reduce_dims = [0] + list(range(2, input.dim()))
        x = input.float()
        local_count = x.numel() // c
        local_sum = x.sum(dim=reduce_dims)
        local_sqsum = (x * x).sum(dim=reduce_dims)

        packed = torch.cat([
            local_sum, local_sqsum,
            torch.tensor([float(local_count)]),
        ])
        packed = mpi_ops.allreduce(packed, op=mpi_ops.Sum,
                                   name="sync_bn.stats",
                                   process_set=process_set)
        g_sum, g_sqsum = packed[:c], packed[c:2 * c]
        g_count = packed[2 * c].item()

        mean = g_sum / g_count
        var = g_sqsum / g_count - mean * mean
        invstd = torch.rsqrt(var + eps)

        if running_mean is not None:
            with torch.no_grad():
                unbiased = var * g_count / max(g_count - 1, 1)
                running_mean.mul_(1 - momentum).add_(momentum * mean)
                running_var.mul_(1 - momentum).add_(momentum * unbiased)

        shape = [1, c] + [1] * (input.dim() - 2)
        x_hat = (x - mean.view(shape)) * invstd.view(shape)
        out = x_hat
        if weight is not None:
            out = out * weight.view(shape).float()
        if bias is not None:
            out = out + bias.view(shape).float()

        ctx.save_for_backward(x_hat, weight, mean, invstd)
        ctx.g_count = g_count
        ctx.process_set = process_set
        ctx.reduce_dims = reduce_dims
        ctx.shape = shape
        return out.to(input.dtype)

    @staticmethod
    def backward(ctx, grad_output):
        x_hat, weight, mean, invstd = ctx.saved_tensors
        g = grad_output.float()
        reduce_dims, shape = ctx.reduce_dims, ctx.shape
        c = x_hat.shape[1]

        sum_dy = g.sum(dim=reduce_dims)
        sum_dy_xhat = (g * x_hat).sum(dim=reduce_dims)

        # grads of weight/bias are LOCAL sums; autograd-level DP
        # averaging (DistributedOptimizer) handles their reduction like
        # any other parameter grad.  With affine=False the forward's
        # weight/bias inputs are None (not Variables), so autograd
        # requires None gradients at those positions.
        grad_weight = (sum_dy_xhat
                       if weight is not None and ctx.needs_input_grad[1]
                       else None)
        grad_bias = sum_dy if ctx.needs_input_grad[2] else None

        packed = torch.cat([sum_dy, sum_dy_xhat])
        packed = mpi_ops.allreduce(packed, op=mpi_ops.Sum,
                                   name="sync_bn.grad",
                                   process_set=ctx.process_set)
        g_sum_dy, g_sum_dy_xhat = packed[:c], packed[c:]

        n = ctx.g_count
        w = (weight.view(shape).float() if weight is not None else 1.0)
        grad_input = (
            w * invstd.view(shape) * (
                g - (g_sum_dy.view(shape)
                     + x_hat * g_sum_dy_xhat.view(shape)) / n
            )
        ).to(grad_output.dtype)

        return (grad_input, grad_weight, grad_bias,
                None, None, None, None, None)
