"""Out-of-scope integration hooks (SURVEY.md §7.3): present, importable,
and clearly refusing."""

import pytest


def test_spark_hook_refuses_clearly():
    import horovod_tpu.spark as spark

    with pytest.raises(NotImplementedError, match="hvtpurun"):
        spark.run(lambda: None)
    with pytest.raises(NotImplementedError):
        spark.TorchEstimator()


def test_ray_hook_refuses_clearly():
    import horovod_tpu.ray as ray_mod

    with pytest.raises(NotImplementedError, match="hvtpurun"):
        ray_mod.RayExecutor()
