"""Pallas TPU kernel tests (ops/pallas_ops.py).

The kernels are the TPU analog of the reference's hand-written device
kernels (horovod/common/ops/cuda/cuda_kernels.cu scale-buffer kernels;
MemcpyInFusionBuffer pack path).  On the CPU test platform the kernel
bodies execute under the Pallas interpreter (HVTPU_PALLAS_INTERPRET=1)
and must agree exactly with the pure-XLA twin lowering the production
fallback uses — the same executable-spec pattern as test_native.py's
C++/Python cross-check.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.ops import (
    QBLOCK,
    dequantize_int8_blocks,
    fused_scale_cast,
    quantize_int8_blocks,
)
from horovod_tpu.ops import pallas_ops


@pytest.fixture
def interpret_mode(monkeypatch):
    monkeypatch.setenv("HVTPU_PALLAS_INTERPRET", "1")


def _rand(n, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(n).astype(np.float32)
    )


class TestFusedScaleCast:
    @pytest.mark.parametrize("n", [17, 1024, 32768, 40000])
    @pytest.mark.parametrize("out_dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_xla(self, interpret_mode, n, out_dtype):
        x = _rand(n)
        got = fused_scale_cast(x, 0.125, out_dtype)
        want = (x * 0.125).astype(out_dtype)
        assert got.shape == (n,)
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_xla_fallback_identical(self, interpret_mode, monkeypatch):
        x = _rand(5000, seed=3)
        kernel = fused_scale_cast(x, 2.0, jnp.bfloat16)
        monkeypatch.setenv("HVTPU_PALLAS", "0")
        xla = fused_scale_cast(x, 2.0, jnp.bfloat16)
        np.testing.assert_array_equal(np.asarray(kernel), np.asarray(xla))


class TestQuantizeInt8:
    @pytest.mark.parametrize("n", [100, QBLOCK, 3 * QBLOCK + 5, 70000])
    def test_roundtrip_error_bound(self, interpret_mode, n):
        x = _rand(n, seed=1)
        q, scale, n_out = quantize_int8_blocks(x)
        assert n_out == n
        assert q.dtype == jnp.int8
        out = dequantize_int8_blocks(q, scale, n)
        # absmax block quantisation: error <= scale/2 per block
        per_block_tol = (
            np.asarray(scale).reshape(-1, 1) * 0.51
        )
        err = np.abs(
            np.asarray(out) - np.asarray(x)
        )
        padded = np.zeros(q.shape[0] * 128 // QBLOCK * QBLOCK)
        padded[:n] = err
        blocks = padded.reshape(-1, QBLOCK)
        assert (blocks <= per_block_tol + 1e-7).all()

    def test_kernel_matches_xla_twin(self, interpret_mode, monkeypatch):
        x = _rand(9000, seed=2)
        qk, sk, _ = quantize_int8_blocks(x)
        monkeypatch.setenv("HVTPU_PALLAS", "0")
        qx, sx, _ = quantize_int8_blocks(x)
        # kernel pads rows further than the twin; the shared prefix must
        # be byte-identical (codes AND scales)
        rows = qx.shape[0]
        np.testing.assert_array_equal(np.asarray(qk)[:rows], np.asarray(qx))
        np.testing.assert_array_equal(
            np.asarray(sk)[: sx.shape[0]], np.asarray(sx)
        )
        # padding region quantises zeros -> zero codes
        assert not np.asarray(qk)[rows:].any()

    def test_zero_block_scale(self, interpret_mode):
        x = jnp.zeros((2048,), jnp.float32)
        q, scale, n = quantize_int8_blocks(x)
        assert not np.asarray(q).any()
        out = dequantize_int8_blocks(q, scale, n)
        assert not np.asarray(out).any()


class TestInt8CompressorIntegration:
    def test_compressor_uses_block_layout(self):
        from horovod_tpu.comm.compression import Compression

        x = _rand(5000, seed=4).reshape(50, 100)
        wire, ctx = Compression.int8.compress(x)
        assert wire.dtype == jnp.int8
        assert wire.shape[1] == Compression.int8.BLOCK
        back = Compression.int8.decompress(wire, ctx)
        assert back.shape == x.shape
        amax = float(jnp.max(jnp.abs(x)))
        assert float(jnp.max(jnp.abs(back - x))) <= amax / 127 * 0.51 + 1e-7

    def test_stochastic_falls_back_deterministic_off_tpu(self):
        from horovod_tpu.comm.compression import Compression

        x = _rand(3000, seed=5)
        w1, c1 = Compression.int8_stochastic.compress(x)
        w2, c2 = Compression.int8_stochastic.compress(x)
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
        back = Compression.int8_stochastic.decompress(w1, c1)
        amax = float(jnp.max(jnp.abs(x)))
        assert float(jnp.max(jnp.abs(back - x))) <= amax / 127 * 0.51 + 1e-7
