"""Cluster-integration surfaces (SURVEY.md §2.6): function-style APIs
run in LOCAL MODE through the hvtpurun machinery (real worker
processes, per-rank results — the reference's own localhost-as-cluster
CI pattern); the Spark Estimator / Ray-placement pieces stay
out-of-scope stubs that refuse clearly (§7.3)."""

import pytest


def _make_rank_size():
    # nested closure: cloudpickle ships it by value, so workers don't
    # need this test module importable (the test_multiprocess pattern)
    def _rank_size():
        import horovod_tpu as hvt

        hvt.init()
        return (hvt.rank(), hvt.size())

    return _rank_size


class TestSparkLocalMode:
    def test_run_executes_fn_per_rank(self):
        import horovod_tpu.spark as spark

        results = spark.run(_make_rank_size(), num_proc=2)
        assert results == [(0, 2), (1, 2)]

    def test_estimator_surface_is_real(self):
        """Round-4: the Estimator stubs became the real surface
        (tests/test_spark_estimator.py carries the behavior; this
        pins the reference import paths + param validation)."""
        import horovod_tpu.spark as spark
        from horovod_tpu.spark.keras import KerasEstimator as KE
        from horovod_tpu.spark.torch import TorchEstimator as TE

        assert spark.TorchEstimator is TE  # horovod.spark.torch parity
        assert spark.KerasEstimator is KE  # horovod.spark.keras parity
        est = spark.TorchEstimator(epochs=2)
        assert est.getEpochs() == 2
        with pytest.raises(ValueError, match="model param"):
            est.fit({"f": [1.0]})
        assert callable(spark.run_elastic)


class TestRayLocalMode:
    def test_executor_lifecycle(self):
        import horovod_tpu.ray as ray_mod

        fn = _make_rank_size()
        # reference world-size arithmetic honored
        assert ray_mod.RayExecutor(num_hosts=2,
                                   num_workers_per_host=4).num_workers == 8
        with pytest.raises(ValueError, match="conflicting"):
            ray_mod.RayExecutor(num_workers=3, num_hosts=2,
                                num_workers_per_host=4)
        ex = ray_mod.RayExecutor(num_workers=2)
        with pytest.raises(RuntimeError, match="start"):
            ex.run(fn)
        ex.start()
        results = ex.run(fn)
        assert results == [(0, 2), (1, 2)]
        assert ex.execute(ex.run_remote(fn)) == [(0, 2), (1, 2)]
        # reference shape: execute(fn) runs it on every worker
        assert ex.execute(fn) == [(0, 2), (1, 2)]
        ex.shutdown()
        with pytest.raises(RuntimeError):
            ex.run(fn)

    def test_elastic_ray_executor_runs(self):
        """Round-4: ElasticRayExecutor became real (lifecycle over the
        elastic driver; fn follows the elastic contract)."""
        import horovod_tpu.ray as ray_mod

        def body():
            import jax.numpy as jnp

            import horovod_tpu as hvt
            import horovod_tpu.elastic as elastic

            hvt.init()
            state = elastic.ObjectState(epoch=0)

            @elastic.run
            def train(state):
                while state.epoch < 2:
                    hvt.allreduce(jnp.ones(2), op=hvt.Sum)
                    state.epoch += 1
                    state.commit()
                return hvt.rank()

            r = train(state)
            hvt.shutdown()
            return (r, state.epoch)

        # reference settings-object style carries the elastic bounds
        s = ray_mod.ElasticRayExecutor.create_settings(min_np=1,
                                                       max_np=2)
        ex = ray_mod.ElasticRayExecutor(s)
        assert ex.min_workers == 1 and ex.num_workers == 2
        with pytest.raises(RuntimeError, match="start"):
            ex.run(body)
        ex.start()
        assert ex.run(body) == [(0, 2), (1, 2)]
        ex.shutdown()
        # an unsatisfiable min must fail fast, not hang to the elastic
        # timeout (min alone is fine — it sets the world size)
        assert ray_mod.ElasticRayExecutor(min_workers=4).num_workers == 4
        with pytest.raises(ValueError, match="min_workers"):
            ray_mod.ElasticRayExecutor(num_workers=2, min_workers=4)
