"""Generic bounded retry with exponential backoff + full jitter.

One policy engine for every "transiently unreachable" surface in the
stack, replacing ad-hoc loops:

- **coordination KV** (``ResilientKV``): the stall inspector's
  heartbeat reads/writes and ``obs/metrics.aggregate``'s snapshot
  exchange ride the JAX coordination service, whose gRPC channel can
  blip (coordinator restart, DCN hiccup, injected fault).  Before this
  module a single ``UNAVAILABLE`` turned into an instant
  ``HorovodInternalError``/hang; now it retries with backoff and only
  an exhausted budget surfaces.  Retries and exhaustions are counted in
  the metrics registry (``hvtpu_kv_retries_total``,
  ``hvtpu_kv_retry_exhausted_total``).
- **gloo teardown races** (``GLOO_TEARDOWN``): jaxlib's gloo CPU
  transport occasionally drops a connection under parallel localhost
  load (a rank SIGSEGVs; peers report "Connection closed by peer").
  That race lives below this framework; the bounded retry the tests
  carried inline is now this named policy, reused from
  ``tests/test_multiprocess.py`` and ``tests/test_launch_cli.py``.

Backoff follows the AWS "full jitter" scheme: sleep is uniform in
``[0, min(max_delay, base * 2**attempt)]`` — decorrelated retries so P
ranks hammering a recovering coordinator don't re-collide in lockstep.

Env knobs (docs/robustness.md):

- ``HVTPU_KV_RETRY_ATTEMPTS``   (default 4)  total attempts per KV op
- ``HVTPU_KV_RETRY_BASE_MS``    (default 50) first-retry backoff cap
- ``HVTPU_KV_RETRY_MAX_MS``     (default 2000) per-sleep cap
- ``HVTPU_KV_RETRY_DEADLINE_S`` (default 30) wall-clock budget per op
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Any, Callable, Optional, Tuple

from ..obs import flight
from ..obs import metrics as obs_metrics
from . import clock
from . import faults

_M_KV_RETRIES = obs_metrics.counter(
    "hvtpu_kv_retries_total",
    "Coordination-KV operations retried after a transient failure.")
_M_KV_EXHAUSTED = obs_metrics.counter(
    "hvtpu_kv_retry_exhausted_total",
    "Coordination-KV operations that failed even after exhausting the "
    "retry budget (the error then surfaces to the caller).")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry schedule + classification.

    ``retryable`` classifies exceptions; ``retry_result`` (optional)
    classifies RETURN VALUES that should be retried (subprocess results
    carrying an infra-crash signature, say).  ``max_attempts`` counts
    total attempts including the first; ``deadline_s`` bounds the whole
    call in wall-clock time.  ``base_delay_s`` of 0 retries immediately
    (the gloo policy: the race is gone on re-run, waiting buys nothing).
    """

    name: str
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    deadline_s: Optional[float] = None
    retryable: Callable[[BaseException], bool] = lambda e: True
    retry_result: Optional[Callable[[Any], bool]] = None

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter sleep before retry ``attempt`` (1-based)."""
        if self.base_delay_s <= 0:
            return 0.0
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** (attempt - 1)))
        return rng.uniform(0.0, cap)


class RetryExhausted(Exception):
    """Raised only for result-based exhaustion when the caller asked
    for it; exception-based exhaustion re-raises the original error so
    existing ``except`` clauses keep matching."""


def call(policy: RetryPolicy, fn: Callable, *args,
         on_retry: Optional[Callable[[int, Optional[BaseException]],
                                     None]] = None,
         rng: Optional[random.Random] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under ``policy``.

    On a retryable exception: sleep (full jitter) and re-attempt until
    ``max_attempts`` or ``deadline_s`` runs out, then re-raise the
    LAST exception (no wrapper type — callers' handlers keep working).
    With ``retry_result``, a True-classified return value is retried
    the same way and the final value is returned once the budget is
    spent.  ``on_retry(attempt, exc_or_None)`` fires before each sleep.
    """
    rng = rng or random.Random()
    start = clock.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            result = fn(*args, **kwargs)
        except Exception as e:
            budget_left = (
                attempt < policy.max_attempts
                and (policy.deadline_s is None
                     or clock.monotonic() - start < policy.deadline_s))
            if not policy.retryable(e) or not budget_left:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            clock.sleep(policy.backoff_s(attempt, rng))
            continue
        if (policy.retry_result is not None
                and policy.retry_result(result)
                and attempt < policy.max_attempts
                and (policy.deadline_s is None
                     or clock.monotonic() - start < policy.deadline_s)):
            if on_retry is not None:
                on_retry(attempt, None)
            clock.sleep(policy.backoff_s(attempt, rng))
            continue
        return result


def retrying(policy: RetryPolicy):
    """Decorator form of :func:`call`."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call(policy, fn, *args, **kwargs)
        return wrapped
    return deco


# ---------------------------------------------------------------------------
# named policies
# ---------------------------------------------------------------------------

# Transient coordination-service failure signatures (grpc status names
# + socket-level shapes).  NOT_FOUND is deliberately absent: a missing
# key is a legitimate answer for try_get, not a failure to retry.
_KV_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED",
    "failed to connect", "Connection reset", "connection reset",
    "Broken pipe", "Socket closed", "coordination service",
)


def kv_retryable(e: BaseException) -> bool:
    if isinstance(e, TimeoutError):
        return True
    msg = str(e)
    return any(m in msg for m in _KV_TRANSIENT_MARKERS)


def kv_blocking_retryable(e: BaseException) -> bool:
    """Blocking-get variant: a NOT_FOUND/timeout just means the peer
    hasn't posted yet — poll again until the caller's deadline."""
    return kv_retryable(e) or "NOT_FOUND" in str(e)


def kv_policy(deadline_s: Optional[float] = None) -> RetryPolicy:
    """The coordination-KV policy, env-tunable (module docstring)."""
    return RetryPolicy(
        name="kv",
        max_attempts=int(os.environ.get("HVTPU_KV_RETRY_ATTEMPTS", "4")),
        base_delay_s=float(
            os.environ.get("HVTPU_KV_RETRY_BASE_MS", "50")) / 1000.0,
        max_delay_s=float(
            os.environ.get("HVTPU_KV_RETRY_MAX_MS", "2000")) / 1000.0,
        deadline_s=(float(os.environ.get("HVTPU_KV_RETRY_DEADLINE_S",
                                         "30"))
                    if deadline_s is None else deadline_s),
        retryable=kv_retryable,
    )


#: jaxlib/gloo CPU-transport teardown-race signatures (a rank SIGSEGVs
#: mid-collective; peers see the torn socket).  Shared by the policy
#: below and the test-suite launch retries.
GLOO_INFRA_MARKERS: Tuple[str, ...] = (
    "Connection closed by peer", "Socket closed",
    "collective transport failure", "connection reset by peer",
)


def is_gloo_infra_error(text: str) -> bool:
    """True when ``text`` (an exception string or a process's combined
    output) carries a gloo teardown-race signature rather than a
    framework failure."""
    return any(m in text for m in GLOO_INFRA_MARKERS)


def gloo_teardown_policy(max_attempts: int = 5,
                         retry_result: Optional[Callable[[Any], bool]]
                         = None) -> RetryPolicy:
    """Bounded relaunch for the gloo CPU teardown race: immediate
    re-run (the race is load-timing, not state), exception-classified
    by :func:`is_gloo_infra_error`; pass ``retry_result`` to also
    classify completed-subprocess results (rc + output blob)."""
    return RetryPolicy(
        name="gloo-teardown",
        max_attempts=max_attempts,
        base_delay_s=0.0,
        retryable=lambda e: is_gloo_infra_error(str(e)),
        retry_result=retry_result,
    )


GLOO_TEARDOWN = gloo_teardown_policy()


# ---------------------------------------------------------------------------
# resilient coordination-KV wrapper
# ---------------------------------------------------------------------------


class ResilientKV:
    """Coordination-service client wrapper: fault injection (sites
    ``kv.get`` / ``kv.put``) + bounded retry with backoff on transient
    failures, counting into the metrics registry.

    Dropped-op semantics (the ``drop`` fault action): a dropped read is
    a miss (``KeyError`` for try_get — the same "no such key" contract
    the raw client's error has, which every caller already treats as
    absent; ``[]`` for dir_get; ``TimeoutError`` for blocking_get), a
    dropped write/delete silently does nothing.  ``blocking_key_value_get``
    is NOT retried here — its callers own a deadline loop already.

    Attributes the wrapped client lacks stay missing (``key_value_dir_get``
    presence is how comm/stall.py picks amortized vs strict mode), and
    unknown attributes delegate, so the wrapper is drop-in.
    """

    def __init__(self, client, rank: int = 0,
                 policy: Optional[RetryPolicy] = None):
        self._kv = client
        self._rank = rank
        self._policy = policy or kv_policy()
        self._rng = random.Random(0x6B76 + rank)
        if hasattr(client, "key_value_dir_get"):
            # instance attribute, so ``getattr(kv, "key_value_dir_get",
            # None)`` stays None for clients without a dir get
            self.key_value_dir_get = self._dir_get

    def _on_retry(self, attempt: int, exc) -> None:
        _M_KV_RETRIES.inc()
        if flight.ACTIVE:
            flight.note("kv_retry", rank=self._rank, attempt=attempt,
                        error=type(exc).__name__)

    def _call(self, fn, *args):
        try:
            return call(self._policy, fn, *args,
                        on_retry=self._on_retry, rng=self._rng)
        except Exception as e:
            if kv_retryable(e):
                _M_KV_EXHAUSTED.inc()
                if flight.ACTIVE:
                    flight.note("kv_retry_exhausted", rank=self._rank,
                                error=str(e)[:200])
            raise

    # Fault injection happens INSIDE the retried closures below, so an
    # ``error``-injected op (whose message carries UNAVAILABLE) is
    # retried exactly like a real coordinator blip — and heals once the
    # clause's budget is spent.  ``drop`` never raises, so it is never
    # retried: a dropped write stays dropped.

    # -- mutations (site kv.put) ---------------------------------------
    def key_value_set(self, key: str, value: str):
        def _put():
            if faults.ACTIVE and faults.inject("kv.put", detail=key):
                return None
            return self._kv.key_value_set(key, value)

        return self._call(_put)

    def key_value_delete(self, key: str):
        if faults.ACTIVE and faults.inject("kv.put", detail=key):
            return None
        # best-effort by contract (callers swallow failures); one shot
        return self._kv.key_value_delete(key)

    # -- reads (site kv.get) -------------------------------------------
    def key_value_try_get(self, key: str):
        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=key):
                raise KeyError(f"{key} (dropped by fault injection)")
            return self._kv.key_value_try_get(key)

        return self._call(_get)

    def _dir_get(self, prefix: str):
        def _get():
            if faults.ACTIVE and faults.inject("kv.get", detail=prefix):
                return []
            return self._kv.key_value_dir_get(prefix)

        return self._call(_get)

    def blocking_key_value_get(self, key: str, timeout_ms: int):
        if faults.ACTIVE and faults.inject("kv.get", detail=key):
            raise TimeoutError(f"{key} (dropped by fault injection)")
        return self._kv.blocking_key_value_get(key, timeout_ms)

    def __getattr__(self, name):
        return getattr(self._kv, name)


def resilient_kv(client, rank: int = 0,
                 policy: Optional[RetryPolicy] = None):
    """Wrap ``client`` (idempotently) in :class:`ResilientKV`."""
    if client is None or isinstance(client, ResilientKV):
        return client
    return ResilientKV(client, rank=rank, policy=policy)
