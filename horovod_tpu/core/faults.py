"""Deterministic, seeded fault injection for the coordination layer.

The elastic stack exists to survive failures — dropped KV ops,
suppressed heartbeats, workers dying mid-collective — yet none of those
can be produced on purpose without this module: the recovery paths
would only ever be exercised by accident.  "Demystifying NCCL"
(PAPERS.md) documents hung/aborted collectives as the dominant
large-job failure mode on GPU stacks; this registry lets CI reproduce
that class of failure deterministically on localhost.

Driven by ``HVTPU_FAULT_SPEC`` (mirrored by ``hvtpurun --fault-spec``).
Grammar (full reference in docs/robustness.md)::

    SPEC   := CLAUSE (";" CLAUSE)*
    CLAUSE := SITE ":" ACTION ("@" SEL ("," SEL)*)?
    SITE   := kv.get | kv.put | heartbeat | collective.pre
            | collective.post | worker.step | data.next
            | ckpt.write | ckpt.fsync | ckpt.rename
            | wire.send | wire.recv | collective.exec
    ACTION := drop | delay(MS) | error | kill | preempt
            | corrupt | corrupt(nan) | corrupt(bitflip)
            | torn | bitflip | partition(MS)
            | slow(MS) | flap(MS)
    SEL    := rank=R[|R...] | pset=ID | count=N | prob=P | times=K

Examples::

    worker.step:kill@rank=1,count=3      # rank 1 dies at its 3rd step
    worker.step:preempt@rank=1,count=3   # rank 1 gets a preemption
                                         # notice at its 3rd step and
                                         # drains (core/preempt.py)
    kv.put:error@prob=0.01               # 1% of KV writes fail (seeded)
    heartbeat:drop@rank=0,count=5,times=20   # beats 5..24 suppressed
    collective.pre:delay(250)@rank=2     # rank 2 lags every collective
    ckpt.write:torn@prob=0.1             # 10% of snapshot payload
                                         # writes truncated mid-file
    ckpt.rename:kill@rank=0,count=2      # rank 0 dies at its 2nd
                                         # commit-rename (torn commit)
    kv.put:partition(3000)@rank=3,count=5   # from rank 3's 5th KV
                                         # write, ALL of its kv.get/
                                         # kv.put/heartbeat traffic is
                                         # dropped for 3 seconds (a
                                         # network partition, not a
                                         # single lost op)
    wire.send:drop@rank=0,count=2        # rank 0's 2nd wire send is
                                         # lost — the consensus abort-
                                         # and-retry path (comm/
                                         # wirefault.py) must recover
                                         # the collective
    wire.recv:slow(100)@rank=3           # rank 3's link serializes
                                         # 100ms slower (a sick link
                                         # the LinkHealth route-around
                                         # should demote)
    wire.send:flap(2000)@rank=1,count=5  # from rank 1's 5th send, its
                                         # wire link goes DOWN for 2
                                         # seconds: every wire.send/
                                         # wire.recv/collective.exec
                                         # in the window is dropped (a
                                         # flapping link, not one lost
                                         # packet)

Selector semantics:

- ``rank=R`` — only these ranks fire (``|``-separated list).
- ``pset=ID`` — only operations on that process set (sites that carry
  no process-set id never match a pset-selected clause).
- ``count=N`` — fire from the Nth matching invocation on (1-based,
  counted per process per clause).
- ``prob=P`` — fire with probability P from a per-``(seed, rank,
  clause)`` RNG, so a given seed reproduces the same fault schedule.
- ``times=K`` — at most K firings (default: 1 for ``kill``,
  ``preempt`` and ``partition``, unlimited otherwise).  Finite ``times`` persist across elastic incarnations
  through a marker file under ``HVTPU_FAULT_STATE_DIR`` (defaulting to
  the driver-provided ``HVTPU_ELASTIC_STATE_DIR``), so a relaunched
  worker does not replay a one-shot kill forever.

Zero overhead when no spec is installed: hot call sites guard on the
module-level ``ACTIVE`` flag (one attribute read) and never call
``inject`` — see ``comm/eager.py::_record_collective``.
"""

from __future__ import annotations

import logging
import os
import random
import re
import threading
from typing import Dict, List, Optional, Sequence

from . import clock

logger = logging.getLogger("horovod_tpu")

#: Sites the framework threads the harness through.  ``inject`` rejects
#: unknown sites at parse time so a typo'd spec fails loudly at init.
#: ``collective.pre``/``collective.post`` are TENSOR sites: ``corrupt``
#: clauses there poison the collective's input/result on the selected
#: ranks (exercising the non-finite guard and the divergence audit).
#: ``data.next`` fires in the input pipeline's batch-delivery path
#: (data/loader.py): ``delay`` stalls inside the DATA_WAIT trace span
#: (an injected input straggler), ``drop`` loses one batch (the cursor
#: advances past it), ``error`` surfaces a source failure.
#: ``ckpt.write``/``ckpt.fsync``/``ckpt.rename`` are STORAGE sites in
#: the durable commit protocol (core/durable.py): ``torn`` truncates
#: the payload mid-write, ``bitflip`` flips one bit of the written
#: bytes (both detected later by manifest verification), ``drop``
#: suppresses the physical operation, ``kill`` dies mid-commit —
#: exactly the host-loss-during-checkpoint failure the protocol must
#: survive.
SITES = ("kv.get", "kv.put", "heartbeat", "collective.pre",
         "collective.post", "worker.step", "data.next",
         "ckpt.write", "ckpt.fsync", "ckpt.rename",
         "wire.send", "wire.recv", "collective.exec")

_STORAGE_SITES = ("ckpt.write", "ckpt.fsync", "ckpt.rename")

#: WIRE sites: the data plane's collective exchange itself
#: (comm/stall.py dispatch for the real backend; the per-edge hop
#: exchange in sim scenarios).  ``drop`` there loses one send/recv/
#: execution (surfacing as a transport-shaped error the consensus
#: abort-and-retry plane in comm/wirefault.py classifies as
#: retryable), ``slow(MS)`` adds serialization delay on the sick link,
#: and ``flap(MS)`` takes the WHOLE wire link down for a window —
#: every wire-site operation on this rank inside the window is
#: dropped, the link-level analog of ``partition(MS)``.
_WIRE_SITES = ("wire.send", "wire.recv", "collective.exec")

#: Coordination-plane sites a ``partition(MS)`` clause silences as a
#: unit.  Unlike ``drop`` (one lost operation), a fired partition opens
#: a wall-clock window during which EVERY kv.get/kv.put/heartbeat on
#: this rank is suppressed — the from-the-rank's-point-of-view shape of
#: a real network partition, which is what the lease-based self-fencing
#: in core/retry.py and the partitioned-vs-dead classification in
#: comm/stall.py exist to survive.
_PARTITION_SITES = ("kv.get", "kv.put", "heartbeat")

ACTIONS = ("drop", "delay", "error", "kill", "preempt", "corrupt",
           "torn", "bitflip", "partition", "slow", "flap")

#: Module-level fast path: False means ``inject`` is never entered.
ACTIVE = False

_registry: Optional["FaultRegistry"] = None
_lock = threading.Lock()

# Thread-local registry override (fabric simulator): each virtual-rank
# thread gets its own FaultRegistry so clauses with rank= selectors fire
# per VIRTUAL rank inside one process.  _tls_installs keeps the ACTIVE
# fast path truthful while any thread-local registry is armed.
_tls = threading.local()
_tls_installs = 0  # every mutation holds _lock (module-level, so the
# thread-safety pass cannot track it; uninstall()/use() enforce this)


class FaultSpecError(ValueError):
    """Malformed ``HVTPU_FAULT_SPEC`` / ``--fault-spec`` string."""


class InjectedFault(RuntimeError):
    """Raised by the ``error`` action.

    The message carries the grpc-style ``UNAVAILABLE`` marker so the
    coordination-KV retry policy (core/retry.py) classifies an injected
    KV failure as transient — an ``error``-injected ``kv.put`` therefore
    exercises the retry path end to end instead of instantly failing
    the job.
    """

    def __init__(self, clause: "FaultClause", site: str):
        super().__init__(
            f"UNAVAILABLE (hvtpu injected fault: {clause.source} "
            f"at site {site})")
        self.clause = clause


_DELAY_RE = re.compile(r"^delay\((\d+(?:\.\d+)?)\)$")
_CORRUPT_RE = re.compile(r"^corrupt(?:\((nan|bitflip)\))?$")
_PARTITION_RE = re.compile(r"^partition\((\d+(?:\.\d+)?)\)$")
_SLOW_RE = re.compile(r"^slow\((\d+(?:\.\d+)?)\)$")
_FLAP_RE = re.compile(r"^flap\((\d+(?:\.\d+)?)\)$")


class FaultClause:
    """One parsed ``site:action[@selectors]`` clause."""

    __slots__ = ("site", "action", "delay_ms", "corrupt_mode",
                 "partition_ms", "flap_ms", "ranks", "pset", "count",
                 "prob", "times", "index", "source", "_fired", "_seen",
                 "_rng")

    def __init__(self, site: str, action: str, delay_ms: float,
                 ranks: Optional[frozenset], pset: Optional[int],
                 count: int, prob: Optional[float], times: int,
                 index: int, source: str, corrupt_mode: str = "nan",
                 partition_ms: float = 0.0, flap_ms: float = 0.0):
        self.site = site
        self.action = action
        self.delay_ms = delay_ms
        self.corrupt_mode = corrupt_mode
        self.partition_ms = partition_ms
        self.flap_ms = flap_ms
        self.ranks = ranks          # None = all ranks
        self.pset = pset            # None = any process set
        self.count = count          # fire from the count-th match (1-based)
        self.prob = prob            # None = always (subject to count)
        self.times = times          # 0 = unlimited
        self.index = index
        self.source = source
        self._fired = 0             # firings so far (this process + disk)
        self._seen = 0              # matching invocations so far
        self._rng: Optional[random.Random] = None

    def bind(self, rank: int, seed: int, persisted_fired: int):
        """Per-process arming: seed the clause RNG from (seed, rank,
        clause index) so every rank draws an independent but
        reproducible stream, and credit firings persisted by earlier
        incarnations against the ``times`` budget."""
        self._rng = random.Random(f"{seed}/{rank}/{self.index}")
        self._fired = persisted_fired

    def matches(self, rank: int, pset) -> bool:
        if self.ranks is not None and rank not in self.ranks:
            return False
        if self.pset is not None and (pset is None or int(pset) != self.pset):
            return False
        return True

    def should_fire(self) -> bool:
        """Called only for matching invocations; owns the count/prob/
        times bookkeeping (caller holds the registry lock)."""
        if self.times and self._fired >= self.times:
            return False
        self._seen += 1
        if self._seen < self.count:
            return False
        if self.prob is not None and self._rng.random() >= self.prob:
            return False
        self._fired += 1
        return True


def parse_spec(spec: str) -> List[FaultClause]:
    """Parse a fault-spec string into clauses; raises
    :class:`FaultSpecError` with the offending fragment on bad input."""
    clauses: List[FaultClause] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if ":" not in raw:
            raise FaultSpecError(
                f"fault clause {raw!r}: expected 'site:action[@sel,...]'")
        site, rest = raw.split(":", 1)
        site = site.strip()
        if site not in SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: unknown site {site!r} "
                f"(known: {', '.join(SITES)})")
        action_s, _, sel_s = rest.partition("@")
        action_s = action_s.strip()
        delay_ms = 0.0
        corrupt_mode = "nan"
        partition_ms = 0.0
        flap_ms = 0.0
        m = _DELAY_RE.match(action_s)
        mc = _CORRUPT_RE.match(action_s)
        mp = _PARTITION_RE.match(action_s)
        ms = _SLOW_RE.match(action_s)
        mf = _FLAP_RE.match(action_s)
        if m:
            action, delay_ms = "delay", float(m.group(1))
        elif mc:
            action, corrupt_mode = "corrupt", mc.group(1) or "nan"
        elif mp:
            action, partition_ms = "partition", float(mp.group(1))
        elif ms:
            action, delay_ms = "slow", float(ms.group(1))
        elif mf:
            action, flap_ms = "flap", float(mf.group(1))
        elif action_s in ("drop", "error", "kill", "preempt",
                          "torn", "bitflip"):
            action = action_s
        else:
            raise FaultSpecError(
                f"fault clause {raw!r}: unknown action {action_s!r} "
                "(known: drop, delay(MS), error, kill, preempt, "
                "corrupt[(nan|bitflip)], torn, bitflip, partition(MS), "
                "slow(MS), flap(MS))")
        if action in ("torn", "bitflip") and site in _WIRE_SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: action {action!r} damages a "
                f"STORED byte stream and only applies at storage sites "
                f"({', '.join(_STORAGE_SITES)}); wire sites "
                f"({', '.join(_WIRE_SITES)}) carry no durable bytes to "
                f"tear — use drop, slow(MS) or flap(MS) there")
        if action in ("torn", "bitflip") and site not in _STORAGE_SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: action {action!r} only applies "
                f"at storage sites ({', '.join(_STORAGE_SITES)})")
        if action == "corrupt" and site in _WIRE_SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: action 'corrupt' poisons tensor "
                f"payloads and only applies at tensor sites "
                f"(collective.pre, collective.post); wire sites carry "
                f"no tensor to poison — use drop, slow(MS) or flap(MS)")
        if action == "partition" and site not in _PARTITION_SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: action 'partition' only applies "
                f"at coordination sites ({', '.join(_PARTITION_SITES)})")
        if action in ("slow", "flap") and site not in _WIRE_SITES:
            raise FaultSpecError(
                f"fault clause {raw!r}: action {action!r} only applies "
                f"at wire sites ({', '.join(_WIRE_SITES)})")
        ranks = pset = prob = None
        count = 1
        # one-shot by default: a rank dies (kill), departs (preempt),
        # loses the network (partition) or its wire link (flap) at
        # most once per job unless times= says otherwise
        times = 1 if action in ("kill", "preempt", "partition",
                                "flap") else 0
        for sel in filter(None, (s.strip() for s in sel_s.split(","))):
            if "=" not in sel:
                raise FaultSpecError(
                    f"fault clause {raw!r}: selector {sel!r} is not "
                    "key=value")
            k, v = (t.strip() for t in sel.split("=", 1))
            try:
                if k == "rank":
                    ranks = frozenset(int(r) for r in v.split("|"))
                elif k == "pset":
                    pset = int(v)
                elif k == "count":
                    count = int(v)
                    if count < 1:
                        raise ValueError
                elif k == "prob":
                    prob = float(v)
                    if not 0.0 <= prob <= 1.0:
                        raise ValueError
                elif k == "times":
                    times = int(v)
                    if times < 0:
                        raise ValueError
                else:
                    raise FaultSpecError(
                        f"fault clause {raw!r}: unknown selector {k!r} "
                        "(known: rank, pset, count, prob, times)")
            except FaultSpecError:
                raise
            except ValueError:
                raise FaultSpecError(
                    f"fault clause {raw!r}: bad selector value "
                    f"{sel!r}") from None
        clauses.append(FaultClause(
            site, action, delay_ms, ranks, pset, count, prob, times,
            index=len(clauses), source=raw, corrupt_mode=corrupt_mode,
            partition_ms=partition_ms, flap_ms=flap_ms))
    return clauses


class FaultRegistry:
    """The armed per-process fault set.

    ``inject(site)`` walks the (tiny) clause list for that site and
    executes the first firing clause's action.  Returns True when the
    operation should be DROPPED (the caller suppresses it), False
    otherwise; ``error`` raises :class:`InjectedFault`; ``kill``
    hard-exits the process.
    """

    def __init__(self, clauses: Sequence[FaultClause], rank: int = 0,
                 seed: int = 0, state_dir: Optional[str] = None,
                 exit_fn=None):
        self.rank = rank
        self.seed = seed
        self.state_dir = state_dir
        # sim seam: ``kill`` calls exit_fn(1) instead of os._exit so a
        # virtual rank can die without taking the host process with it
        self._exit_fn = exit_fn
        self._lock = threading.Lock()
        # a fired partition(MS) clause opens a window on the (possibly
        # virtual) clock during which EVERY _PARTITION_SITES operation
        # on this registry is dropped — one clause, full silence
        self._partition_until = 0.0  # hvtpulint: guarded-by(_lock)
        # a fired flap(MS) clause opens the same kind of window over
        # the WIRE sites: the rank's data-plane link is down, every
        # wire.send/wire.recv/collective.exec in the window is dropped
        self._flap_until = 0.0  # hvtpulint: guarded-by(_lock)
        self._by_site: Dict[str, List[FaultClause]] = {}
        for c in clauses:
            c.bind(rank, seed, self._load_fired(c))
            self._by_site.setdefault(c.site, []).append(c)

    # -- cross-incarnation persistence ---------------------------------
    def _marker(self, clause: FaultClause) -> Optional[str]:
        if not self.state_dir or not clause.times:
            return None
        return os.path.join(self.state_dir, "faults_fired",
                            f"clause_{clause.index}")

    def _load_fired(self, clause: FaultClause) -> int:
        path = self._marker(clause)
        if not path:
            return 0
        try:
            with open(path) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _persist_fired(self, clause: FaultClause) -> None:
        path = self._marker(clause)
        if not path:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(str(clause._fired))
        except OSError:
            logger.warning("fault harness: could not persist firing "
                           "count to %s", path, exc_info=True)

    # -- the injection point -------------------------------------------
    def _select(self, site: str, pset, tensor_site: bool,
                storage_site: bool = False) -> Optional[FaultClause]:
        """First firing clause for ``site``.  ``corrupt`` clauses only
        fire at tensor sites (``inject_tensor``) — plain ``inject``
        call sites carry no data to poison, and silently consuming the
        firing there would make the clause look like a no-op.  The
        same argument gates ``torn``/``bitflip`` to storage call sites
        (``inject_storage``): only there is a byte stream to damage."""
        with self._lock:
            for clause in self._by_site.get(site, ()):
                if clause.action == "corrupt" and not tensor_site:
                    continue
                if (clause.action in ("torn", "bitflip")
                        and not storage_site):
                    continue
                if clause.matches(self.rank, pset) and clause.should_fire():
                    return clause
        return None

    def _execute(self, fired: FaultClause, site: str,
                 detail: Optional[str]) -> bool:
        """Run a fired clause's non-tensor action; returns True for
        ``drop`` (caller suppresses the operation)."""
        # Persist BEFORE executing: a kill must be counted by the next
        # incarnation even though this process never returns.
        self._persist_fired(fired)
        logger.warning(
            "hvtpu fault injection: firing [%s] at site %s (rank %d%s)",
            fired.source, site, self.rank,
            f", op {detail}" if detail else "")
        if fired.action in ("delay", "slow"):
            clock.sleep(fired.delay_ms / 1000.0)
            return False
        if fired.action == "drop":
            return True
        if fired.action == "flap":
            until = clock.monotonic() + fired.flap_ms / 1000.0
            with self._lock:
                self._flap_until = max(self._flap_until, until)
            from ..obs import flight as _flight

            if _flight.ACTIVE:
                _flight.note("link_flap_start", rank=self.rank,
                             window_ms=fired.flap_ms, site=site)
            return True  # the triggering op is the window's first loss
        if fired.action == "partition":
            until = clock.monotonic() + fired.partition_ms / 1000.0
            with self._lock:
                self._partition_until = max(self._partition_until, until)
            from ..obs import flight as _flight

            if _flight.ACTIVE:
                _flight.note("partition_start", rank=self.rank,
                             window_ms=fired.partition_ms, site=site)
            return True  # the triggering op is the window's first loss
        if fired.action == "error":
            raise InjectedFault(fired, site)
        if fired.action == "preempt":
            # deliver a preemption notice instead of dying: the
            # graceful-drain path (core/preempt.py) takes it from here
            # — persisted above like kill, so the relaunched rank does
            # not re-preempt forever.
            from . import preempt as _preempt

            _preempt.notice("fault")
            return False
        # kill: flush and hard-exit — simulate a worker dying mid-op
        # (exit 1 = crash, NOT the reset code: the driver must treat
        # this as an unplanned death, exactly like a real one).  The
        # flight recorder flushes its black box first: a killed rank
        # still leaves a postmortem behind.
        import sys

        from ..obs import flight as _flight

        _flight.dump_postmortem("fault_kill", site=site)
        print(f"hvtpu fault injection: killing rank {self.rank} "
              f"([{fired.source}] at {site})", file=sys.stderr, flush=True)
        sys.stdout.flush()
        if self._exit_fn is not None:
            self._exit_fn(1)
            return False
        os._exit(1)

    def partition_remaining(self) -> float:
        """Seconds left in an open partition window (0.0 when none)."""
        with self._lock:
            until = self._partition_until
        return max(0.0, until - clock.monotonic())

    def flap_remaining(self) -> float:
        """Seconds left in an open wire-flap window (0.0 when none)."""
        with self._lock:
            until = self._flap_until
        return max(0.0, until - clock.monotonic())

    def inject(self, site: str, pset=None, detail: Optional[str] = None
               ) -> bool:
        # An open partition window silences every coordination site on
        # this rank before any per-clause selection runs.
        if site in _PARTITION_SITES:
            with self._lock:
                partitioned = (self._partition_until
                               and clock.monotonic() < self._partition_until)
            if partitioned:
                return True
        # Likewise a flapping wire link drops every wire-site op.
        if site in _WIRE_SITES:
            with self._lock:
                flapping = (self._flap_until
                            and clock.monotonic() < self._flap_until)
            if flapping:
                return True
        fired = self._select(site, pset, tensor_site=False)
        if fired is None:
            return False
        return self._execute(fired, site, detail)

    def inject_storage(self, site: str, detail: Optional[str] = None
                       ) -> Optional[str]:
        """Storage-site injection point (``ckpt.*`` in the durable
        commit protocol, core/durable.py).  Returns the damage the
        caller must apply to the physical operation:

        - ``"torn"`` — truncate the payload mid-write;
        - ``"bitflip"`` — flip one bit of the written bytes;
        - ``"drop"`` — suppress the operation entirely (an elided
          fsync or rename IS a torn commit);
        - ``None`` — proceed normally (after any delay; ``error``
          raises, ``kill`` never returns)."""
        fired = self._select(site, None, tensor_site=False,
                             storage_site=True)
        if fired is None:
            return None
        if fired.action in ("torn", "bitflip"):
            self._persist_fired(fired)
            logger.warning(
                "hvtpu fault injection: %s storage damage [%s] at site "
                "%s (rank %d%s)", fired.action, fired.source, site,
                self.rank, f", op {detail}" if detail else "")
            return fired.action
        if self._execute(fired, site, detail):
            return "drop"
        return None

    def inject_tensor(self, site: str, tensor, pset=None,
                      detail: Optional[str] = None):
        """Tensor-site injection point: like :meth:`inject`, but the
        operation carries data, so ``corrupt`` clauses can poison it
        (NaN in element 0, or a flipped sign bit for ``bitflip``/
        non-float dtypes).  Returns the (possibly poisoned) tensor;
        ``drop`` is a no-op here — a collective cannot be suppressed
        without desyncing its peers."""
        fired = self._select(site, pset, tensor_site=True)
        if fired is None:
            return tensor
        if fired.action != "corrupt":
            self._execute(fired, site, detail)
            return tensor
        self._persist_fired(fired)
        logger.warning(
            "hvtpu fault injection: corrupting (%s) [%s] at site %s "
            "(rank %d%s)", fired.corrupt_mode, fired.source, site,
            self.rank, f", op {detail}" if detail else "")
        return _poison(tensor, fired.corrupt_mode)


def _poison(tensor, mode: str):
    """Poison one element of ``tensor``: NaN for float dtypes in
    ``nan`` mode, a flipped top bit of byte 0 otherwise.  Host
    round-trip is fine — injection is never a hot path."""
    import numpy as np

    x = np.array(tensor)  # contiguous host copy
    if x.size == 0:
        return tensor
    flat = x.reshape(-1)
    if mode == "nan" and np.issubdtype(x.dtype, np.floating):
        flat[0] = np.nan
    else:
        raw = flat.view(np.uint8)
        raw[x.dtype.itemsize - 1] ^= 0x80
    try:
        import jax.numpy as jnp

        return jnp.asarray(x)
    except ImportError:  # pragma: no cover - jax is baked in
        return x


def install(spec: str, rank: int = 0, seed: int = 0,
            state_dir: Optional[str] = None) -> Optional[FaultRegistry]:
    """Arm the process-wide registry from a spec string (empty/None
    uninstalls).  Called by ``core.state.init`` once the true rank is
    known; idempotent re-install replaces the previous registry."""
    global _registry, ACTIVE
    with _lock:
        if not spec or not spec.strip():
            _registry = None
            ACTIVE = _tls_installs > 0
            return None
        _registry = FaultRegistry(
            parse_spec(spec), rank=rank, seed=seed, state_dir=state_dir)
        ACTIVE = True
        return _registry


def install_from_config(cfg, rank: int) -> Optional[FaultRegistry]:
    """Arm from a Config snapshot (HVTPU_FAULT_SPEC / HVTPU_FAULT_SEED);
    the persistence dir falls back to the elastic state dir so one-shot
    faults survive driver relaunches without extra wiring."""
    spec = getattr(cfg, "fault_spec", None)
    if not spec:
        return None
    state_dir = (os.environ.get("HVTPU_FAULT_STATE_DIR")
                 or os.environ.get("HVTPU_ELASTIC_STATE_DIR"))
    return install(spec, rank=rank,
                   seed=int(getattr(cfg, "fault_seed", 0) or 0),
                   state_dir=state_dir)


def uninstall() -> None:
    global _registry, ACTIVE
    with _lock:
        _registry = None
        ACTIVE = _tls_installs > 0


def use(reg: Optional[FaultRegistry]) -> None:
    """Install ``reg`` as the CALLING THREAD's fault registry (None to
    uninstall).  The fabric simulator arms one registry per virtual-rank
    thread this way; :func:`inject` / :func:`inject_tensor` on that
    thread then route to it instead of the process-wide registry, and
    the module ``ACTIVE`` fast path stays truthful while any
    thread-local registry is armed."""
    global _tls_installs, ACTIVE
    prev = getattr(_tls, "registry", None)
    _tls.registry = reg
    with _lock:
        if reg is not None and prev is None:
            _tls_installs += 1
        elif reg is None and prev is not None:
            _tls_installs = max(0, _tls_installs - 1)
        ACTIVE = _registry is not None or _tls_installs > 0


def _current() -> Optional[FaultRegistry]:
    reg = getattr(_tls, "registry", None)
    return reg if reg is not None else _registry


def inject(site: str, pset=None, detail: Optional[str] = None) -> bool:
    """Fire any armed clause for ``site``.  Returns True when the
    caller should DROP the operation; may sleep (delay), raise
    :class:`InjectedFault` (error), or never return (kill).  A no-op
    returning False when nothing is installed — but hot paths should
    guard on ``faults.ACTIVE`` and skip the call entirely."""
    reg = _current()
    if reg is None:
        return False
    return reg.inject(site, pset=pset, detail=detail)


def inject_tensor(site: str, tensor, pset=None,
                  detail: Optional[str] = None):
    """Tensor-site variant of :func:`inject`: returns the (possibly
    ``corrupt``-poisoned) tensor; other actions behave as in
    :func:`inject` except ``drop``, which is a no-op at tensor sites.
    Hot paths guard on ``faults.ACTIVE`` before calling."""
    reg = _current()
    if reg is None:
        return tensor
    return reg.inject_tensor(site, tensor, pset=pset, detail=detail)


def inject_storage(site: str, detail: Optional[str] = None
                   ) -> Optional[str]:
    """Storage-site variant of :func:`inject` for the ``ckpt.*`` sites:
    returns the damage mode the caller must apply (``"torn"`` /
    ``"bitflip"`` / ``"drop"``) or None to proceed; ``delay`` sleeps,
    ``error`` raises, ``kill`` never returns.  Checkpoint writes are
    never a hot path, but callers still guard on ``faults.ACTIVE``."""
    reg = _current()
    if reg is None:
        return None
    return reg.inject_storage(site, detail=detail)


def partition_remaining() -> float:
    """Seconds left in the calling thread's open ``partition(MS)``
    window (0.0 when none is armed/open) — test and sim probe."""
    reg = _current()
    if reg is None:
        return 0.0
    return reg.partition_remaining()


def flap_remaining() -> float:
    """Seconds left in the calling thread's open ``flap(MS)`` wire
    window (0.0 when none is armed/open) — test and sim probe."""
    reg = _current()
    if reg is None:
        return 0.0
    return reg.flap_remaining()
