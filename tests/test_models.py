"""Model zoo smoke tests (tiny shapes, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import MLP, ResNet18, ResNet50


class TestResNet:
    def test_resnet50_forward_shapes(self):
        model = ResNet50(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_resnet18_train_mode_updates_stats(self):
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out, mutated = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        assert out.shape == (2, 10)
        assert "batch_stats" in mutated

    def test_resnet_grads_finite(self):
        model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
        x = jnp.ones((2, 32, 32, 3))
        y = jnp.zeros((2,), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)

        def loss_fn(params):
            import optax

            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)


class TestMLP:
    def test_forward(self):
        model = MLP()
        x = jnp.ones((4, 28, 28))
        variables = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(variables, x)
        assert out.shape == (4, 10)
