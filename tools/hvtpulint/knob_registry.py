"""knob-registry pass: HVTPU_* env knobs vs the generated docs/knobs.md.

Extraction sources (all AST-based — error-message strings that merely
mention a knob name do not count):

  * env reads: ``os.environ.get("HVTPU_X")`` / ``os.getenv`` /
    ``environ["HVTPU_X"]`` / ``.pop`` / ``.setdefault``
  * the config helpers: ``_env*("X", default)`` in core/config.py
    expands to HVTPU_X (with the HOROVOD_X compatibility fallback)
  * env writes: launcher-side ``env["HVTPU_X"] = ...`` stores and
    dict-literal keys (worker environment construction)
  * the ``hvtpurun`` CLI binding: the ``flag_env`` map in
    runner/launch.py plus ``add_argument`` flags

The documentation side is the table in docs/knobs.md (regenerated via
``python -m tools.hvtpulint --write-knobs``; descriptions are
hand-written and preserved across regenerations).  Findings:

  * read in code, no table row        -> undocumented-knob
  * table row, never read or written  -> dead-knob
  * table row with a TODO description -> undescribed-knob
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from . import Finding, Project

PASS = "knob-registry"

KNOBS_MD = "docs/knobs.md"
LAUNCH_PY = "horovod_tpu/runner/launch.py"
SCAN_DIRS = ("horovod_tpu", "examples")
SCAN_FILES = ("bench.py", "bench_eager.py", "bench_scaling.py", "setup.py")

_ENV_HELPER_RE = re.compile(r"^_env(_\w+)?$")
_GET_LIKE = {"get", "getenv", "pop", "setdefault"}
_ROW_RE = re.compile(r"^\|\s*`(HVTPU_\w+)`\s*\|(.*)")
PLACEHOLDER = "TODO"


@dataclasses.dataclass
class Knob:
    reads: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    writes: List[Tuple[str, int]] = dataclasses.field(default_factory=list)
    defaults: List[str] = dataclasses.field(default_factory=list)
    cli_flag: str = ""


def _env_receiver(node: ast.expr) -> bool:
    """True when `node` plausibly denotes an environment mapping."""
    try:
        text = ast.unparse(node)
    except Exception:
        return False
    return text == "os" or text == "env" or text.endswith("environ")


def _knob_name(value: ast.expr) -> Optional[str]:
    if (isinstance(value, ast.Constant) and isinstance(value.value, str)
            and value.value.startswith("HVTPU_")
            and len(value.value) > len("HVTPU_")):
        return value.value
    return None


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    """Module-level `NAME = "literal"` bindings (knob names are often
    hoisted into constants, e.g. runner/secret.py's ENV_KEY)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


class _Extractor(ast.NodeVisitor):
    def __init__(self, rel: str, knobs: Dict[str, Knob],
                 consts: Dict[str, str]):
        self.rel = rel
        self.knobs = knobs
        self.consts = consts

    def _knob(self, name: str) -> Knob:
        return self.knobs.setdefault(name, Knob())

    def _resolve(self, value: ast.expr) -> Optional[str]:
        name = _knob_name(value)
        if name is not None:
            return name
        if isinstance(value, ast.Name):
            lit = self.consts.get(value.id, "")
            if lit.startswith("HVTPU_") and len(lit) > len("HVTPU_"):
                return lit
        return None

    def visit_Call(self, node: ast.Call):
        f = node.func
        # os.environ.get("HVTPU_X") / env.pop("HVTPU_X") / os.getenv(...)
        if (isinstance(f, ast.Attribute) and f.attr in _GET_LIKE
                and node.args and _env_receiver(f.value)):
            name = self._resolve(node.args[0])
            if name:
                knob = self._knob(name)
                if f.attr == "setdefault":
                    knob.writes.append((self.rel, node.lineno))
                else:
                    knob.reads.append((self.rel, node.lineno))
                if f.attr in {"get", "getenv"} and len(node.args) > 1:
                    knob.defaults.append(ast.unparse(node.args[1]))
        # config.py helpers: _env("CYCLE_TIME", 1.0) -> HVTPU_CYCLE_TIME;
        # local helpers passing the full name (_env_float("HVTPU_X", d))
        # count as reads of that name verbatim
        if (isinstance(f, ast.Name) and _ENV_HELPER_RE.match(f.id)
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            arg = node.args[0].value
            name = arg if arg.startswith("HVTPU_") else "HVTPU_" + arg
            if len(name) > len("HVTPU_"):
                knob = self._knob(name)
                knob.reads.append((self.rel, node.lineno))
                if len(node.args) > 1:
                    knob.defaults.append(ast.unparse(node.args[1]))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if _env_receiver(node.value):
            name = self._resolve(node.slice)
            if name:
                knob = self._knob(name)
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    knob.writes.append((self.rel, node.lineno))
                else:
                    knob.reads.append((self.rel, node.lineno))
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict):
        # Worker-env / flag_env dict literals keyed by knob name.
        for key in node.keys:
            if key is None:
                continue
            name = _knob_name(key)
            if name:
                self._knob(name).writes.append((self.rel, node.lineno))
        self.generic_visit(node)


def _cli_flags(project: Project, knobs: Dict[str, Knob]) -> None:
    """Attach hvtpurun flag spellings via launch.py's flag_env map."""
    tree = project.parse(LAUNCH_PY)
    if tree is None:
        return
    # argparse dest -> "--flag" spelling
    dest_to_flag: Dict[str, str] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("--")):
            flag = node.args[0].value
            dest = flag.lstrip("-").replace("-", "_")
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
            dest_to_flag[dest] = flag
    # flag_env = {"HVTPU_X": args.attr, ...}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "flag_env"
                and isinstance(node.value, ast.Dict)):
            for key, val in zip(node.value.keys, node.value.values):
                name = _knob_name(key) if key is not None else None
                if not name:
                    continue
                # args.attr, or the getattr(args, "attr", None) spelling
                # launch.py uses for flags absent from older namespaces.
                dest = None
                if isinstance(val, ast.Attribute):
                    dest = val.attr
                elif (isinstance(val, ast.Call)
                        and isinstance(val.func, ast.Name)
                        and val.func.id == "getattr" and len(val.args) >= 2
                        and isinstance(val.args[1], ast.Constant)
                        and isinstance(val.args[1].value, str)):
                    dest = val.args[1].value
                if dest:
                    flag = dest_to_flag.get(dest)
                    if flag and name in knobs:
                        knobs[name].cli_flag = flag


def extract_knobs(project: Project) -> Dict[str, Knob]:
    knobs: Dict[str, Knob] = {}
    files = project.py_files(*SCAN_DIRS)
    for rel in SCAN_FILES:
        p = project.root / rel
        if p.is_file():
            files.append(p)
    for path in files:
        tree = project.parse(path)
        if tree is None:
            continue
        _Extractor(project.rel(path), knobs,
                   _module_str_consts(tree)).visit(tree)
    _cli_flags(project, knobs)
    return knobs


def parse_knobs_md(text: str) -> Dict[str, Tuple[int, str]]:
    """Documented knob -> (line, description column)."""
    out: Dict[str, Tuple[int, str]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        m = _ROW_RE.match(line.strip())
        if not m:
            continue
        cols = [c.strip() for c in m.group(2).split("|")]
        desc = cols[-2] if len(cols) >= 2 else ""
        out[m.group(1)] = (lineno, desc)
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    knobs = extract_knobs(project)
    doc_text = project.read(KNOBS_MD)
    if doc_text is None:
        findings.append(project.missing(PASS, KNOBS_MD))
        return findings
    documented = parse_knobs_md(doc_text)

    for name, knob in sorted(knobs.items()):
        if knob.reads and name not in documented:
            rel, line = knob.reads[0]
            findings.append(Finding(
                PASS, rel, line, name,
                f"undocumented knob {name} — add a row to {KNOBS_MD} "
                "(python -m tools.hvtpulint --write-knobs)"))
    for name, (line, desc) in sorted(documented.items()):
        knob = knobs.get(name)
        if knob is None or (not knob.reads and not knob.writes):
            findings.append(Finding(
                PASS, KNOBS_MD, line, name,
                f"documented knob {name} is never read or written — "
                "dead doc row (or the knob's reader was deleted)"))
        elif not desc or PLACEHOLDER in desc:
            findings.append(Finding(
                PASS, KNOBS_MD, line, f"describe:{name}",
                f"knob {name} has a placeholder description — write "
                "one line of real semantics"))
    return findings


# ---------------------------------------------------------------------------
# docs/knobs.md generation (--write-knobs)
# ---------------------------------------------------------------------------

_HEADER = """\
# Environment knobs

<!-- The knob rows in this file are generated: run
     `python -m tools.hvtpulint --write-knobs` after adding or removing
     an HVTPU_* read.  Edit descriptions in place — regeneration
     preserves them.  The knob-registry lint pass fails on rows that
     drift from the code. -->

Every `HVTPU_*` environment variable the tree reads, with defaults and
the `hvtpurun` flag that sets it (where one exists).  Knobs read
through `core/config.py` also accept a `HOROVOD_*` spelling as a
compatibility fallback.  `HVTPU_SECRET_KEY` is intentionally **not**
forwarded via argv by the launcher — the HMAC key travels in a 0600
file named by `HVTPU_SECRET_FILE` (see runner/launch.py).

| Knob | Default | `hvtpurun` flag | Description |
|---|---|---|---|
"""


def _default_col(knob: Knob) -> str:
    uniq = sorted(set(knob.defaults))
    if not uniq:
        return "(unset)"
    return "`" + "`, `".join(uniq) + "`"


def generate_knobs_md(project: Project) -> str:
    knobs = extract_knobs(project)
    old = project.read(KNOBS_MD)
    existing = parse_knobs_md(old) if old else {}
    rows = []
    for name, knob in sorted(knobs.items()):
        if not knob.reads:
            # Write-only names (e.g. rank wiring the launcher computes)
            # are still documented: workers read them via config.
            pass
        desc = existing.get(name, (0, ""))[1] or PLACEHOLDER
        flag = f"`{knob.cli_flag}`" if knob.cli_flag else ""
        rows.append(f"| `{name}` | {_default_col(knob)} | {flag} | {desc} |")
    return _HEADER + "\n".join(rows) + "\n"


def write_knobs_md(project: Project) -> Path:
    out = project.root / KNOBS_MD
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(generate_knobs_md(project), encoding="utf-8")
    return out
