"""Elastic data-pipeline script used by the exactly-once integration
tests: trains over an ArraySource of DATA_SAMPLES identity samples with
a commit per batch, printing one ``DELIVER`` line per delivered batch
(sample values double as indices) so the harness can assert every
sample arrives exactly once across incarnations/resizes.
"""

import os
import time

import numpy as np

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic
from horovod_tpu.data import ArraySource, ElasticDataLoader


def main():
    hvt.init()
    epochs = int(os.environ.get("ELASTIC_EPOCHS", "2"))
    sleep_s = float(os.environ.get("EPOCH_SLEEP", "0.3"))
    n = int(os.environ.get("DATA_SAMPLES", "48"))
    batch = int(os.environ.get("DATA_BATCH", "4"))
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    loader = ElasticDataLoader(
        ArraySource({"x": x}), batch_size=batch, seed=7,
        device_put=False)
    state = elastic.ObjectState(data=loader.state, total=0.0)

    @elastic.run
    def train(state):
        import jax.numpy as jnp

        gen = os.environ.get("HVTPU_ELASTIC_GENERATION", "0")
        while loader.state.epoch < epochs:
            epoch = loader.state.epoch
            for b in loader:
                idx = sorted(int(v) for v in np.asarray(b["x"]).ravel())
                # a real collective per batch: resize mid-epoch must
                # not deadlock the survivors
                out = hvt.allreduce(jnp.ones(2), op=hvt.Sum)
                state.total += float(out[0])
                print(
                    f"DELIVER rank={hvt.rank()} size={hvt.size()} "
                    f"gen={gen} epoch={epoch} idx={idx}",
                    flush=True,
                )
                time.sleep(sleep_s)
                state.commit()
        if hvt.rank() == 0:
            print(f"DONE size={hvt.size()} epoch={loader.state.epoch}",
                  flush=True)

    train(state)
    loader.close()


if __name__ == "__main__":
    main()
