"""Elastic data-parallel training example.

The horovod_tpu analog of the reference's elastic examples
(examples/elastic/pytorch/pytorch_mnist_elastic.py shape): state
commits every epoch survive worker loss and world resizes.

Run:
  hvtpurun --host-discovery-script ./discover.sh --min-np 2 \
      --cpu-devices 1 python examples/elastic_train.py
where discover.sh prints e.g. "localhost:4".
"""

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic


def main():
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(512, 32).astype(np.float32)
    w_true = rng.randn(32, 1).astype(np.float32)
    y = x @ w_true

    params = {"w": jnp.zeros((32, 1))}
    state = elastic.JaxState(params=params, epoch=0)

    @jax.jit
    def grad_fn(p, bx, by):
        def loss(p):
            return jnp.mean((bx @ p["w"] - by) ** 2)

        return jax.value_and_grad(loss)(p)

    @elastic.run
    def train(state):
        while state.epoch < 8:
            # shard batches by the CURRENT world (resizes survive)
            n = len(x) // hvt.size()
            lo = hvt.rank() * n
            bx, by = jnp.asarray(x[lo:lo + n]), jnp.asarray(y[lo:lo + n])
            loss, grads = grad_fn(state.params, bx, by)
            grads = {
                k: hvt.allreduce(g, op=hvt.Average)
                for k, g in grads.items()
            }
            state.params = jax.tree.map(
                lambda p, g: p - 0.3 * g, state.params, grads
            )
            state.epoch += 1
            state.commit()
            if hvt.rank() == 0:
                print(
                    f"epoch {state.epoch}: loss={float(loss):.5f} "
                    f"(world size {hvt.size()})",
                    flush=True,
                )
        if hvt.rank() == 0:
            print("elastic training complete", flush=True)

    train(state)


if __name__ == "__main__":
    main()
