from . import eager, spmd
from .adasum import adasum_reduce, adasum_reduce_reference
from .compression import Compression
from .fusion import BucketPlan, fused_tree_allreduce, plan_buckets, plan_for_tree
from .reduce_ops import Adasum, Average, Max, Min, Product, ReduceOp, Sum

__all__ = [
    "eager",
    "spmd",
    "adasum_reduce",
    "adasum_reduce_reference",
    "Compression",
    "BucketPlan",
    "fused_tree_allreduce",
    "plan_buckets",
    "plan_for_tree",
    "ReduceOp",
    "Average",
    "Sum",
    "Adasum",
    "Min",
    "Max",
    "Product",
]
