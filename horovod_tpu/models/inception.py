"""Inception V3 in flax.linen, bf16-first.

Third member of the reference's README benchmark trio (Inception V3 /
ResNet-101 / VGG-16 — ``docs/benchmarks.rst``; Inception is its
~90%-scaling compute-bound case).  Standard V3 topology (stem, 3×A,
B, 4×C, D, 2×E, 299×299 input) without the auxiliary head — the
benchmark path never trains it.

TPU notes: bf16 compute, fp32 params/BN stats; NHWC; TpuBatchNorm for
the flattened 2-D stat reduce (see models/tpu_norm.py) with optional
cross-replica sync via ``bn_axis_name``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from .tpu_norm import TpuBatchNorm


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16
    train: bool = True
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = TpuBatchNorm(
            use_running_average=not self.train, momentum=0.9,
            epsilon=1e-3, dtype=self.dtype,
            axis_name=self.bn_axis_name if self.train else None,
        )(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, dtype=self.dtype, train=train,
                       bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)

        # stem (299x299x3 -> 35x35x192)
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x)
        x = conv(32, (3, 3), padding="VALID")(x)
        x = conv(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, (1, 1), padding="VALID")(x)
        x = conv(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))

        # 3x Inception-A
        for pool_features in (32, 64, 64):
            b1 = conv(64, (1, 1))(x)
            b5 = conv(64, (5, 5))(conv(48, (1, 1))(x))
            b3 = conv(96, (3, 3))(conv(96, (3, 3))(conv(64, (1, 1))(x)))
            bp = conv(pool_features, (1, 1))(_avg_pool_same(x))
            x = jnp.concatenate([b1, b5, b3, bp], axis=-1)

        # Inception-B (grid reduction 35 -> 17)
        b3 = conv(384, (3, 3), (2, 2), padding="VALID")(x)
        bd = conv(96, (3, 3), (2, 2), padding="VALID")(
            conv(96, (3, 3))(conv(64, (1, 1))(x)))
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = jnp.concatenate([b3, bd, bp], axis=-1)

        # 4x Inception-C with factorized 7x7
        for c7 in (128, 160, 160, 192):
            b1 = conv(192, (1, 1))(x)
            b7 = conv(192, (7, 1))(conv(c7, (1, 7))(conv(c7, (1, 1))(x)))
            bdbl = conv(c7, (1, 1))(x)
            bdbl = conv(c7, (1, 7))(conv(c7, (7, 1))(bdbl))
            bdbl = conv(192, (7, 1))(conv(c7, (1, 7))(bdbl))
            bp = conv(192, (1, 1))(_avg_pool_same(x))
            x = jnp.concatenate([b1, b7, bdbl, bp], axis=-1)

        # Inception-D (grid reduction 17 -> 8)
        b3 = conv(320, (3, 3), (2, 2), padding="VALID")(
            conv(192, (1, 1))(x))
        b7 = conv(192, (1, 7))(conv(192, (1, 1))(x))
        b7 = conv(192, (3, 3), (2, 2), padding="VALID")(
            conv(192, (7, 1))(b7))
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = jnp.concatenate([b3, b7, bp], axis=-1)

        # 2x Inception-E
        for _ in range(2):
            b1 = conv(320, (1, 1))(x)
            b3 = conv(384, (1, 1))(x)
            b3 = jnp.concatenate(
                [conv(384, (1, 3))(b3), conv(384, (3, 1))(b3)], axis=-1)
            bd = conv(384, (3, 3))(conv(448, (1, 1))(x))
            bd = jnp.concatenate(
                [conv(384, (1, 3))(bd), conv(384, (3, 1))(bd)], axis=-1)
            bp = conv(192, (1, 1))(_avg_pool_same(x))
            x = jnp.concatenate([b1, b3, bd, bp], axis=-1)

        # head: flattened spatial mean (same TPU reduce note as ResNet)
        n, h, w, c = x.shape
        x = jnp.mean(x.reshape(n, h * w, c), axis=1)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
