"""CLI for the fleet arbiter (see package docstring)."""

from __future__ import annotations

import argparse
import json
import os
import sys


def _fleet_dir(args) -> str:
    d = args.fleet_dir or os.environ.get("HVTPU_FLEET_DIR")
    if not d:
        print("hvtpufleet: --fleet-dir (or HVTPU_FLEET_DIR) is required",
              file=sys.stderr)
        raise SystemExit(2)
    return d


def _cmd_serve(args) -> int:
    from horovod_tpu.elastic.discovery import HostDiscoveryScript
    from horovod_tpu.fleet import FleetArbiter

    d = _fleet_dir(args)
    os.makedirs(os.path.join(d, "submit"), exist_ok=True)
    os.makedirs(os.path.join(d, "cancel"), exist_ok=True)
    arbiter = FleetArbiter(
        HostDiscoveryScript(args.host_discovery_script),
        fleet_dir=d,
        tick_s=args.tick,
        drain_grace_s=args.drain_grace,
        verbose=not args.quiet,
    )
    try:
        recovered = arbiter.recover()
        if recovered and not args.quiet:
            print(f"hvtpufleet: recovered {recovered} job(s) from "
                  "state.json", file=sys.stderr)
        arbiter.run(until_idle=args.until_idle)
    except KeyboardInterrupt:
        pass
    finally:
        arbiter.close()
    if args.until_idle:
        state = arbiter.debug_state()
        failed = [j["name"] for j in state["jobs"]
                  if j["state"] == "FAILED"]
        if failed:
            print(f"hvtpufleet: jobs failed: {', '.join(failed)}",
                  file=sys.stderr)
            return 1
    return 0


def _cmd_submit(args) -> int:
    from horovod_tpu.fleet.intake import QueueFullError, SubmitJournal
    from horovod_tpu.fleet.job import FleetSpecError, JobSpec

    # client-side validation: a malformed spec never reaches the
    # journal
    try:
        spec = JobSpec.load(args.spec)
    except FleetSpecError as e:
        print(f"hvtpufleet: --spec: {e}", file=sys.stderr)
        return 2
    d = _fleet_dir(args)
    journal = SubmitJournal(d)
    try:
        seq = journal.append_submit(spec.to_dict())
    except QueueFullError as e:
        # truthful backpressure: the arbiter's published drain rate
        # says when the backlog will be below the limit again
        print(f"hvtpufleet: {e}", file=sys.stderr)
        return 75  # EX_TEMPFAIL: retry later, nothing was queued
    print(f"hvtpufleet: submitted {spec.name!r} as journal #{seq} "
          f"(priority={spec.priority}, min_np={spec.min_np})")
    return 0


def _cmd_list(args) -> int:
    d = _fleet_dir(args)
    path = os.path.join(d, "state.json")
    try:
        with open(path) as f:
            state = json.load(f)
    except OSError:
        print(f"hvtpufleet: no state at {path} — is an arbiter "
              f"serving this fleet dir?", file=sys.stderr)
        return 1
    if args.json:
        json.dump(state, sys.stdout, sort_keys=True, indent=1)
        print()
        return 0
    pool = state.get("pool", {})
    print(f"pool: {pool.get('slots_total', 0)} slots "
          f"({pool.get('slots_free', 0)} free) across "
          f"{len(pool.get('hosts', {}))} hosts")
    rows = [("JOB", "STATE", "PRI", "NP", "WAIT_S", "REASON")]
    for j in state.get("jobs", []):
        rows.append((
            j.get("name", "?"), j.get("state", "?"),
            str(j.get("priority", 0)),
            str(sum((j.get("allocation") or {}).values())),
            f"{j.get('queue_wait_s') or 0:.1f}",
            (j.get("reason") or "")[:40],
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return 0


def _fmt_health(j):
    """One `top` row's health columns from a job's attached summary
    (written by the arbiter's health poll into state.json)."""
    h = j.get("health")
    if not isinstance(h, dict):
        return "-", "-", "-", "-"
    rate = h.get("step_rate")
    incid = h.get("incidents_total", 0)
    restarts = h.get("restarts", 0)
    stall = h.get("stall_age_s") or 0.0
    stale = " *" if h.get("stale") else ""
    return (f"{rate:.2f}{stale}" if isinstance(rate, (int, float))
            else "-",
            str(incid), str(restarts),
            f"{stall:.0f}" if stall else "-")


def _cmd_top(args) -> int:
    d = _fleet_dir(args)
    path = os.path.join(d, "state.json")
    try:
        with open(path) as f:
            state = json.load(f)
    except OSError:
        print(f"hvtpufleet: no state at {path} — is an arbiter "
              f"serving this fleet dir?", file=sys.stderr)
        return 1
    pool = state.get("pool", {})
    print(f"pool: {pool.get('slots_total', 0)} slots "
          f"({pool.get('slots_free', 0)} free); "
          f"as of t={state.get('t_wall', 0)}")
    rows = [("JOB", "STATE", "NP", "STEP/S", "INCID", "RESTARTS",
             "STALL_S")]
    for j in state.get("jobs", []):
        rate, incid, restarts, stall = _fmt_health(j)
        rows.append((
            j.get("name", "?"), j.get("state", "?"),
            str(sum((j.get("allocation") or {}).values())),
            rate, incid, restarts, stall,
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    print("(* = stale health summary; job stopped publishing)")
    return 0


def _cmd_cancel(args) -> int:
    from horovod_tpu.fleet.intake import SubmitJournal

    d = _fleet_dir(args)
    # journal, not a marker file: the cancel record is ordered AFTER
    # the job's submit record, so a spooled-but-not-yet-intaken job is
    # tombstoned before it can ever go PENDING
    seq = SubmitJournal(d).append_cancel(args.name)
    print(f"hvtpufleet: cancel requested for {args.name!r} "
          f"(journal #{seq})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hvtpufleet",
        description="Operate a multi-job hvtpu fleet arbiter.")
    ap.add_argument("--fleet-dir", default=None,
                    help="Fleet spool/state directory "
                    "(default: $HVTPU_FLEET_DIR).")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="Run the arbiter loop.")
    s.add_argument("--host-discovery-script", required=True,
                   help="Script printing 'host:slots' lines for the "
                   "shared pool.")
    s.add_argument("--tick", type=float, default=None,
                   help="Arbiter tick period in seconds "
                   "(default: $HVTPU_FLEET_TICK_SECONDS or 1).")
    s.add_argument("--drain-grace", type=float, default=None,
                   help="Seconds a preemption victim gets to drain "
                   "before SIGTERM escalation (default: "
                   "$HVTPU_FLEET_DRAIN_GRACE_SECONDS or 30).")
    s.add_argument("--until-idle", action="store_true",
                   help="Exit once every submitted job is terminal "
                   "(nonzero if any FAILED).")
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=_cmd_serve)

    s = sub.add_parser("submit", help="Validate and spool a job spec.")
    s.add_argument("--spec", required=True,
                   help="Path to the job-spec JSON.")
    s.set_defaults(fn=_cmd_submit)

    s = sub.add_parser("list", help="Show pool and job states.")
    s.add_argument("--json", action="store_true",
                   help="Raw state.json instead of the table.")
    s.set_defaults(fn=_cmd_list)

    s = sub.add_parser(
        "top", help="Per-job health: step rate, incidents, restarts, "
        "stall age.")
    s.set_defaults(fn=_cmd_top)

    s = sub.add_parser("cancel", help="Request cancellation of a job.")
    s.add_argument("name", help="Job name to cancel.")
    s.set_defaults(fn=_cmd_cancel)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
