"""Stall watchdog for the SYNC eager data plane.

Parity surface: ``horovod/common/stall_inspector.cc``
(``StallInspector::CheckForStalledTensors`` /
``InvalidateStalledCachedResponses``) — the reference's coordinator
names every tensor some rank has submitted that others haven't, warns
after ``HOROVOD_STALL_CHECK_TIME_SECONDS`` and aborts after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.  The *async* path here has
its own inspector inside the eager mini-controller
(``eager/controller.py``); this module covers the **sync** ops in
``comm/eager.py``, which otherwise enter an XLA collective that simply
blocks forever when a rank diverges — the classic Horovod deadlock
this subsystem exists to catch (SURVEY §5.2 calls it essential).

TPU-native design: an XLA collective cannot be interrupted once
entered, so detection must happen **before** dispatch.  Every sync
collective performs a cheap KV rendezvous over the JAX coordination
service (the store that already hosts init and the async controller's
transport): post ``stall/<gen>/<set>/<seq>/<rank> = op-descriptor``,
then await the other member ranks' marks for the same sequence number.
Arrival order per (process set) is rank-consistent by the SPMD
contract, so the sequence number needs no negotiation.  Outcomes:

- all marks arrive (normal case: one try_get per peer) → dispatch;
- a peer's mark carries a DIFFERENT descriptor → the ranks have
  diverged onto different collectives — raise immediately, naming
  both ops (the reference logs this as a mismatched-tensor error);
- past ``stall_check_time_seconds`` → warn, naming the op, the wait,
  and exactly which ranks are absent (repeats each interval);
- past ``stall_shutdown_time_seconds`` (when > 0) → raise
  ``HorovodInternalError`` instead of hanging — which the elastic
  ``run`` decorator already catches as a recoverable failure, so a
  stalled elastic job rolls back and re-rendezvouses like the
  reference's shutdown-on-stall path.

The async controller's cycle thread executes its (already negotiated)
responses through the same ``comm/eager`` functions; it registers
itself via ``bypass_thread()`` so those dispatches skip the
rendezvous.  Nested internal collectives (barrier's allreduce,
reducescatter's uneven-path allreduce) rendezvous on their own — the
nesting is part of the op's implementation, hence identical on every
rank, so the extra checks stay consistent and only refine diagnostics.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from ..core import state as core_state
from ..core.exceptions import HorovodInternalError

logger = logging.getLogger("horovod_tpu")

_NS = "hvtstall"
_tls = threading.local()


def bypass_thread():
    """Mark the CURRENT thread's eager collectives as exempt from the
    sync rendezvous (used by the async controller's cycle thread, whose
    op order is already negotiated and stall-inspected)."""
    _tls.bypass = True


class SyncStallInspector:
    """Per-process rendezvous bookkeeping over the coordination KV."""

    def __init__(self, client, rank: int, warn_s: float, abort_s: float,
                 generation: int = 0):
        self._kv = client
        self.rank = rank
        self.warn_s = warn_s
        self.abort_s = abort_s
        self.gen = generation
        self._seq: Dict[int, int] = {}

    # -- key helpers --------------------------------------------------
    def _key(self, set_id: int, seq: int, rank: int) -> str:
        return f"{_NS}/{self.gen}/{set_id}/{seq}/{rank}"

    def _try_get(self, key: str) -> Optional[str]:
        try:
            return self._kv.key_value_try_get(key)
        except Exception:
            return None

    def _marks(self, set_id: int, seq: int) -> Optional[Dict[int, str]]:
        """All posted marks for (set, seq) in ONE RPC via the KV's
        directory get — the happy path costs one roundtrip regardless
        of P.  Returns None when the client has no usable dir-get
        (test fakes, older clients), so the caller can fall back to
        per-rank try_get; {} means 'working, nothing posted yet'."""
        prefix = f"{_NS}/{self.gen}/{set_id}/{seq}/"
        dir_get = getattr(self._kv, "key_value_dir_get", None)
        if dir_get is None:
            return None
        try:
            return {int(k.rsplit("/", 1)[-1]): v
                    for k, v in dir_get(prefix)}
        except Exception:
            return None

    # -- the rendezvous -----------------------------------------------
    def rendezvous(self, set_id: int, member_ranks, desc: str):
        """Block until every member rank posts a mark for this set's
        next sequence number; warn/abort on deadline."""
        seq = self._seq.get(set_id, 0)
        self._seq[set_id] = seq + 1
        self._kv.key_value_set(self._key(set_id, seq, self.rank), desc)

        pending = [r for r in member_ranks if r != self.rank]
        start = time.monotonic()
        next_warn = self.warn_s
        sleep = 0.0
        use_dir = True
        while pending:
            found = self._marks(set_id, seq) if use_dir else None
            if found is None:
                use_dir = False
                found = {}
                for r in pending:
                    val = self._try_get(self._key(set_id, seq, r))
                    if val is not None:
                        found[r] = val
            still = []
            for r in pending:
                val = found.get(r)
                if val is None:
                    still.append(r)
                elif val != desc:
                    raise HorovodInternalError(
                        f"collective mismatch at process set {set_id} "
                        f"op #{seq}: this rank ({self.rank}) is entering "
                        f"[{desc}] but rank {r} posted [{val}]. Ranks "
                        "have diverged onto different collectives; this "
                        "would deadlock or corrupt the wire."
                    )
            pending = still
            if not pending:
                break
            elapsed = time.monotonic() - start
            if self.abort_s > 0 and elapsed > self.abort_s:
                raise HorovodInternalError(
                    f"stalled collective [{desc}] (process set {set_id}, "
                    f"op #{seq}): waited {elapsed:.1f}s > stall shutdown "
                    f"time {self.abort_s:.1f}s; ranks not at the "
                    f"rendezvous: {pending}. One or more ranks skipped "
                    "this collective or died before reaching it."
                )
            if self.warn_s > 0 and elapsed > next_warn:
                next_warn += self.warn_s
                logger.warning(
                    "stalled collective [%s] (process set %d, op #%d): "
                    "waited %.1fs; ranks not at the rendezvous: %s",
                    desc, set_id, seq, elapsed, pending,
                )
            # back off from a near-spin (normal skew is sub-ms) to a
            # 20ms poll for genuinely late peers
            sleep = min(0.02, sleep * 2 if sleep else 0.0002)
            time.sleep(sleep)

        # rolling cleanup: every member has posted seq, so nobody can
        # still be waiting on marks older than seq — drop our own
        # previous mark to keep the KV bounded (each rank deletes only
        # its own keys; no cross-rank races)
        if seq > 0:
            try:
                self._kv.key_value_delete(
                    self._key(set_id, seq - 1, self.rank))
            except Exception:
                pass


def check(st, ps, desc: str) -> None:
    """The eager ops' pre-dispatch hook: rendezvous with the other
    member ranks (the XLA collective entered next is uninterruptible),
    or no-op when stall checking cannot or should not engage (single
    member, controller thread, disabled, no coordination client)."""
    if ps.size <= 1 or getattr(_tls, "bypass", False):
        return
    cfg = st.config
    if cfg is None or cfg.stall_check_disable:
        return
    inspector = st.sync_stall
    if inspector is None:
        try:
            from jax._src import distributed as _jd

            client = _jd.global_state.client
        except Exception:
            client = None
        if client is None:
            st.sync_stall = False
            return
        inspector = SyncStallInspector(
            client, st.rank,
            warn_s=cfg.stall_check_time_seconds,
            abort_s=cfg.stall_shutdown_time_seconds,
            generation=st.init_generation,
        )
        st.sync_stall = inspector
    elif inspector is False:
        return
    members = ps.ranks if ps.ranks is not None else range(st.size)
    inspector.rendezvous(ps.process_set_id, list(members), desc)
