"""Estimator/Model base classes — the shared fit/transform lifecycle.

Parity surface: ``horovod/spark/common/estimator.py``
(``HorovodEstimator``, ``HorovodModel``): ``fit(df)`` materializes the
DataFrame into the Store, launches distributed training through the
Backend (one Horovod rank per process), loads the trained artifacts
back on the driver, and returns a Model whose ``transform(df)`` appends
prediction columns.  The reference subclasses pyspark's
``Estimator``/``Model``; here the same lifecycle runs over pandas /
dict-of-columns frames (pyspark frames are accepted and collected —
see common.data), so the surface works with or without a Spark
installation.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List

from . import data as data_mod
from .backend import Backend, LocalBackend
from .params import EstimatorParams, Params


def resolve_compression(hvd_frontend, value):
    """Estimator ``compression`` param → frontend compressor class.
    Accepts the reference's style (a compressor object/class, e.g.
    ``hvd.Compression.fp16``) or a name string; a typo gets a clear
    error naming the options.  Shared by the torch and keras
    trainers."""
    if value is None:
        return hvd_frontend.Compression.none
    if isinstance(value, str):
        comp = getattr(hvd_frontend.Compression, value, None)
        if comp is None or value.startswith("_"):
            options = [a for a in dir(hvd_frontend.Compression)
                       if not a.startswith("_") and a != "from_name"]
            raise ValueError(
                f"unknown compression {value!r}; options: {options}")
        return comp
    return value


class HorovodEstimator(EstimatorParams):
    """fit(df) → trained HorovodModel, over Store + Backend."""

    # -- subclass hooks ----------------------------------------------
    def _remote_trainer(self):
        """Module-level worker function (rides the launcher's signed
        pickle channel by reference, not by value)."""
        raise NotImplementedError

    def _serialize_training_spec(self) -> Dict[str, Any]:
        """Framework-specific picklable bundle shipped to every rank."""
        raise NotImplementedError

    def _create_model(self, rank_results: List[Any], run_id: str,
                      store) -> "HorovodModel":
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------
    def _check_params(self):
        if self.getModel() is None:
            raise ValueError("model param is required")
        if not self.getFeatureCols():
            raise ValueError("feature_cols param is required")
        if not self.getLabelCols():
            raise ValueError("label_cols param is required")
        if self.getStore() is None:
            raise ValueError(
                "store param is required (e.g. LocalStore(prefix)) — "
                "it holds materialized data and run checkpoints")
        if self.getResumeFromCheckpoint() and not self.getRunId():
            raise ValueError(
                "resume_from_checkpoint=True requires an explicit "
                "run_id (each fit otherwise generates a fresh run id "
                "whose checkpoint path cannot exist — the resume "
                "would silently no-op)")
        if self.getSampleWeightCol() is not None \
                and self.getTransformationFn() is not None:
            raise ValueError(
                "sample_weight_col cannot be combined with "
                "transformation_fn: the transform may reorder or "
                "resize rows and the weight column would silently "
                "misalign; fold the weighting into the "
                "transformation instead")

    def _resolve_backend(self) -> Backend:
        backend = self.getBackend()
        if backend is None:
            backend = LocalBackend(num_proc=self.getNumProc() or 2)
        return backend

    def fit(self, df) -> "HorovodModel":
        self._check_params()
        store = self.getStore()
        backend = self._resolve_backend()
        run_id = self.getRunId() or f"run_{uuid.uuid4().hex[:12]}"
        n_train, n_val = data_mod.materialize(
            df, store,
            feature_cols=list(self.getFeatureCols()),
            label_cols=list(self.getLabelCols()),
            validation=self.getValidation(),
            sample_weight_col=self.getSampleWeightCol(),
            seed=self.getRandomSeed(),
        )
        spec = self._serialize_training_spec()
        spec.update(
            store_prefix=store.prefix_path,
            run_id=run_id,
            n_train=n_train,
            n_val=n_val,
            params={
                k: v for k, v in self.param_dict().items()
                # objects that must not ride the wire (store/backend are
                # driver-side; model/loss/... travel inside `spec`)
                if k not in ("store", "backend", "model", "loss",
                             "optimizer", "custom_objects", "callbacks",
                             "metrics", "transformation_fn")
            },
        )
        results = backend.run(self._remote_trainer(), args=(spec,))
        return self._create_model(results, run_id, store)


class HorovodModel(Params):
    """Trained-model half of the lifecycle (reference: HorovodModel).

    ``transform(df)`` appends prediction columns named by
    ``output_cols`` (default ``<label>__output``, the reference's
    convention); ``getHistory()`` exposes per-epoch training history.
    """

    _param_defs = {
        "model": None,
        "feature_cols": None,
        "label_cols": None,
        "output_cols": None,
        "run_id": None,
        "store": None,
        "history": None,
        "batch_size": 128,
    }

    def _predict_columns(self, features: Dict[str, Any]) -> List[Any]:
        """Framework forward pass → list of per-output-column arrays."""
        raise NotImplementedError

    def _output_col_names(self) -> List[str]:
        out = self.getOutputCols()
        if out:
            return list(out)
        return [f"{c}__output" for c in self.getLabelCols()]

    def transform(self, df):
        """Append prediction columns; returns the same frame kind it
        was given (pandas → pandas copy, dict → dict copy, pyspark →
        pandas)."""
        if hasattr(df, "toPandas") and not hasattr(df, "assign"):
            df = df.toPandas()  # collect ONCE; to_columns reuses it
        features = data_mod.to_columns(df, list(self.getFeatureCols()))
        outputs = self._predict_columns(features)
        names = self._output_col_names()
        if len(outputs) != len(names):
            raise ValueError(
                f"model produced {len(outputs)} output column(s) but "
                f"output_cols names {len(names)}: {names}")
        if isinstance(df, dict):
            out = dict(df)
            out.update(zip(names, outputs))
            return out
        return df.assign(**dict(zip(names, outputs)))
