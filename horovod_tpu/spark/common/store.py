"""Store abstraction for Spark Estimator intermediate data + checkpoints.

Parity surface: ``horovod/spark/common/store.py`` (``Store``,
``FilesystemStore``, ``LocalStore``, ``HDFSStore``) — the reference's
Store owns three path families per training run: materialized train/val
data, per-run checkpoints, and per-run logs, plus small read/write
helpers the estimators use for metadata.

TPU-native scope: the sandbox's durable medium is a (shared) local
filesystem — the same medium the launcher's function/result channel and
the sharded elastic checkpoints already ride — so ``FilesystemStore``
is the real implementation and ``LocalStore`` its alias (mirroring the
reference, where LocalStore is FilesystemStore pinned to ``file://``).
Object stores (HDFS/S3/GCS/DBFS) raise with a pointer: zero-egress
sandbox, and a TPU pod's NFS/persistent-disk mount serves the same
role.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional


class Store:
    """Abstract run/data/checkpoint path layout for estimators.

    Matches the reference's surface: ``get_train_data_path()``,
    ``get_val_data_path()``, ``get_run_path(run_id)``,
    ``get_checkpoint_path(run_id)``, ``get_logs_path(run_id)``,
    ``exists()/read()/write_text()``, and the ``create(prefix)``
    factory that picks an implementation from the path scheme.
    """

    @classmethod
    def create(cls, prefix_path: str, *args, **kwargs) -> "Store":
        scheme = prefix_path.split("://", 1)[0] if "://" in prefix_path \
            else "file"
        if scheme in ("file", ""):
            return FilesystemStore(prefix_path, *args, **kwargs)
        raise NotImplementedError(
            f"store scheme {scheme!r}: object-store backends (HDFS/S3/"
            "GCS/DBFS) are out of scope in this build — mount the "
            "bucket (gcsfuse/NFS) and use a file:// prefix, or "
            "subclass Store (parity: horovod/spark/common/store.py)."
        )

    # -- path layout -------------------------------------------------
    def get_full_path(self, path: str) -> str:
        raise NotImplementedError

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        raise NotImplementedError

    def get_data_metadata_path(self) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    # -- small IO helpers the estimators use -------------------------
    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write_text(self, path: str, text: str) -> None:
        raise NotImplementedError

    def saving_runs(self) -> bool:
        """Whether checkpoints/logs are persisted (reference knob)."""
        raise NotImplementedError


class FilesystemStore(Store):
    """Store over a plain filesystem prefix (shared FS on a pod).

    Layout under ``prefix_path`` (mirrors the reference's):
    ``intermediate_train_data/``, ``intermediate_val_data/``,
    ``runs/<run_id>/checkpoints/``, ``runs/<run_id>/logs/``.
    """

    def __init__(self, prefix_path: str, save_runs: bool = True):
        self.prefix_path = self._strip_scheme(prefix_path)
        self._save_runs = save_runs
        os.makedirs(self.prefix_path, exist_ok=True)

    @staticmethod
    def _strip_scheme(p: str) -> str:
        return p[len("file://"):] if p.startswith("file://") else p

    def get_full_path(self, path: str) -> str:
        path = self._strip_scheme(path)
        if os.path.isabs(path):
            return path
        return os.path.join(self.prefix_path, path)

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        base = os.path.join(self.prefix_path, "intermediate_train_data")
        return base if idx is None else os.path.join(base, f"part_{idx}")

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        base = os.path.join(self.prefix_path, "intermediate_val_data")
        return base if idx is None else os.path.join(base, f"part_{idx}")

    def get_data_metadata_path(self) -> str:
        return os.path.join(self.prefix_path, "intermediate_train_data",
                            "_metadata.json")

    def get_runs_path(self) -> str:
        return os.path.join(self.prefix_path, "runs")

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.get_runs_path(), run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoints")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def get_checkpoints(self, run_id: str,
                        suffix: str = "") -> List[str]:
        """Checkpoint filenames for a run, sorted (reference helper)."""
        d = self.get_checkpoint_path(run_id)
        if not os.path.isdir(d):
            return []
        return sorted(f for f in os.listdir(d) if f.endswith(suffix))

    def exists(self, path: str) -> bool:
        return os.path.exists(self.get_full_path(path))

    def read(self, path: str) -> bytes:
        with open(self.get_full_path(path), "rb") as f:
            return f.read()

    def write_text(self, path: str, text: str) -> None:
        full = self.get_full_path(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = full + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, full)

    def read_json(self, path: str):
        return json.loads(self.read(path).decode())

    def saving_runs(self) -> bool:
        return self._save_runs


class LocalStore(FilesystemStore):
    """Reference alias: a FilesystemStore on node-local disk."""
