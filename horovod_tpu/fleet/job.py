"""Per-job spec, validation, lifecycle state machine, and job-scoped
coordination-KV prefixing for the fleet arbiter.

A *job* is one elastic workload sharing the pool with others: a
command, a priority tier, and a min/max world size.  The arbiter
(:mod:`.arbiter`) owns the lifecycle; this module owns the pieces that
are pure data + validation:

- :class:`JobSpec` — the submit-time contract.  ``from_dict`` /
  ``load`` validate every field and raise :class:`FleetSpecError`
  naming exactly the malformed field, so ``hvtpufleet submit --spec``
  can fail fast with a precise diagnostic (exit 2), mirroring
  ``hvtpurun --fault-spec`` validation.

- The lifecycle state machine::

      PENDING → RUNNING → DONE | FAILED
                   ↓  ↑
               DRAINING → RESIZING → RUNNING

  ``DRAINING`` means an arbiter-initiated planned shrink (priority
  preemption or autoscale) is in flight through the core/preempt.py
  notice channel; ``RESIZING`` covers the window between the drain
  commit and the relaunched incarnation.  Transitions are validated —
  an illegal edge is an arbiter bug, not a recoverable condition.

- :func:`prefixed_client` — a coordination-KV wrapper that namespaces
  every key under ``fleet/<job>/``, so N jobs sharing one KV (the
  simulator's SimFabric; a future shared coordination service) can
  never read each other's drain notices, audit sequences, or elect
  markers.  The wrapper mirrors only the capability tiers the inner
  client actually has (``dir``/``bytes`` probing, same idiom as the
  drain coordinator's ``_dir_entries`` fallback).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

from ..core import clock

__all__ = [
    "DONE",
    "DRAINING",
    "FAILED",
    "FleetSpecError",
    "Job",
    "JobSpec",
    "PENDING",
    "RESIZING",
    "RUNNING",
    "STATES",
    "prefixed_client",
]

PENDING = "PENDING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
RESIZING = "RESIZING"
DONE = "DONE"
FAILED = "FAILED"

#: Every lifecycle state, in display order (state.json, /debug, gauges).
STATES = (PENDING, RUNNING, DRAINING, RESIZING, DONE, FAILED)

# Legal edges.  DRAINING → RUNNING covers a coarse arbiter tick that
# never observes the intermediate RESIZING phase; DRAINING/RESIZING →
# DONE covers a job finishing while its shrink is still in flight.
_TRANSITIONS = {
    PENDING: {RUNNING, FAILED},
    RUNNING: {DRAINING, RESIZING, DONE, FAILED},
    DRAINING: {RESIZING, RUNNING, DONE, FAILED},
    RESIZING: {RUNNING, DONE, FAILED},
    DONE: set(),
    FAILED: set(),
}


class FleetSpecError(ValueError):
    """A malformed job spec; ``field`` names the offending field so the
    CLI diagnostic (and the unit matrix) can be exact."""

    def __init__(self, field: str, message: str):
        super().__init__(f"field '{field}': {message}")
        self.field = field


# The name becomes a directory (state dir, notice dir) and a KV prefix:
# restrict it accordingly.
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

_SPEC_FIELDS = (
    "name", "command", "priority", "min_np", "max_np", "env",
    "max_restarts", "restart_window", "drain_grace", "autoscale",
    "tenant",
)
_AUTOSCALE_FIELDS = (
    "signal_file", "high", "low", "step", "debounce_s", "cooldown_s",
)


def _require_int(field: str, value: Any, minimum: int) -> int:
    if isinstance(value, bool) or not isinstance(value, int):
        raise FleetSpecError(
            field, f"must be an integer (got {value!r})")
    if value < minimum:
        raise FleetSpecError(
            field, f"must be >= {minimum} (got {value})")
    return value


def _require_num(field: str, value: Any, minimum: float) -> float:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise FleetSpecError(
            field, f"must be a number (got {value!r})")
    if value < minimum:
        raise FleetSpecError(
            field, f"must be >= {minimum:g} (got {value})")
    return float(value)


class JobSpec:
    """The submit-time contract for one fleet job."""

    def __init__(self, name: str, command: List[str], *,
                 priority: int = 0, min_np: int = 1,
                 max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 max_restarts: int = -1, restart_window: float = 0.0,
                 drain_grace: Optional[float] = None,
                 autoscale: Optional[Dict[str, Any]] = None,
                 tenant: Optional[str] = None):
        self.name = name
        # a bare string must reach validate() intact (list("cmd")
        # would explode into single-char "arguments" that pass)
        self.command = (list(command)
                        if isinstance(command, (list, tuple))
                        else command)
        self.priority = priority
        self.min_np = min_np
        self.max_np = max_np
        self.env = (dict(env) if isinstance(env, dict)
                    else ({} if env is None else env))
        self.max_restarts = max_restarts
        self.restart_window = restart_window
        self.drain_grace = drain_grace
        self.autoscale = dict(autoscale) if autoscale else None
        self.tenant = tenant
        self.validate()

    # -- validation -----------------------------------------------------
    def validate(self) -> None:
        """Raise :class:`FleetSpecError` naming the first malformed
        field (the ``hvtpufleet submit --spec`` exit-2 contract)."""
        if not isinstance(self.name, str) or not _NAME_RE.match(
                self.name):
            raise FleetSpecError(
                "name",
                "must match [A-Za-z0-9][A-Za-z0-9._-]{0,63} — it names "
                f"the job's state dir and KV prefix (got {self.name!r})")
        if (not isinstance(self.command, list) or not self.command
                or not all(isinstance(c, str) and c
                           for c in self.command)):
            raise FleetSpecError(
                "command",
                "must be a non-empty list of non-empty strings "
                f"(got {self.command!r})")
        self.priority = _require_int("priority", self.priority, 0)
        self.min_np = _require_int("min_np", self.min_np, 1)
        if self.max_np is not None:
            _require_int("max_np", self.max_np, 1)
            if self.max_np < self.min_np:
                raise FleetSpecError(
                    "max_np",
                    f"must be >= min_np={self.min_np} "
                    f"(got {self.max_np})")
        if not isinstance(self.env, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in self.env.items()):
            raise FleetSpecError(
                "env", f"must be a string→string map (got {self.env!r})")
        self.max_restarts = _require_int(
            "max_restarts", self.max_restarts, -1)
        self.restart_window = _require_num(
            "restart_window", self.restart_window, 0.0)
        if self.drain_grace is not None:
            self.drain_grace = _require_num(
                "drain_grace", self.drain_grace, 0.5)
        if self.tenant is not None and (
                not isinstance(self.tenant, str)
                or not _NAME_RE.match(self.tenant)):
            raise FleetSpecError(
                "tenant",
                "must match [A-Za-z0-9][A-Za-z0-9._-]{0,63} — it keys "
                f"the admission-control quota table (got "
                f"{self.tenant!r})")
        if self.autoscale is not None:
            self._validate_autoscale()

    def _validate_autoscale(self) -> None:
        a = self.autoscale
        if not isinstance(a, dict):
            raise FleetSpecError(
                "autoscale", f"must be an object (got {a!r})")
        for k in a:
            if k not in _AUTOSCALE_FIELDS:
                raise FleetSpecError(
                    f"autoscale.{k}",
                    "unknown field (known: "
                    f"{', '.join(_AUTOSCALE_FIELDS)})")
        for k in ("high", "low"):
            if k not in a:
                raise FleetSpecError(
                    f"autoscale.{k}", "required (signal watermark)")
            _require_num(f"autoscale.{k}", a[k], 0.0)
        if a["low"] >= a["high"]:
            raise FleetSpecError(
                "autoscale.low",
                f"must be < autoscale.high={a['high']} "
                f"(got {a['low']})")
        if "signal_file" in a and (
                not isinstance(a["signal_file"], str)
                or not a["signal_file"]):
            raise FleetSpecError(
                "autoscale.signal_file",
                f"must be a non-empty path (got {a['signal_file']!r})")
        if "step" in a:
            _require_int("autoscale.step", a["step"], 1)
        for k in ("debounce_s", "cooldown_s"):
            if k in a:
                _require_num(f"autoscale.{k}", a[k], 0.0)

    def effective_max(self, cap: Optional[int] = None) -> int:
        """The largest world this job may run at, optionally capped by
        the pool."""
        m = self.max_np if self.max_np is not None else (
            cap if cap is not None else self.min_np)
        return min(m, cap) if cap is not None else m

    # -- (de)serialisation ----------------------------------------------
    @classmethod
    def from_dict(cls, d: Any) -> "JobSpec":
        if not isinstance(d, dict):
            raise FleetSpecError(
                "spec", f"must be a JSON object (got {type(d).__name__})")
        for k in d:
            if k not in _SPEC_FIELDS:
                raise FleetSpecError(
                    k, f"unknown field (known: {', '.join(_SPEC_FIELDS)})")
        for k in ("name", "command"):
            if k not in d:
                raise FleetSpecError(k, "required")
        kwargs = {k: v for k, v in d.items()
                  if k not in ("name", "command")}
        return cls(d["name"], d["command"], **kwargs)

    @classmethod
    def load(cls, path: str) -> "JobSpec":
        """Read + validate a spec file; JSON syntax errors surface as
        ``FleetSpecError('spec', ...)`` so the CLI's exit-2 path is
        uniform."""
        try:
            with open(path) as f:
                raw = json.load(f)
        except OSError as e:
            raise FleetSpecError("spec", f"unreadable: {e}") from e
        except ValueError as e:
            raise FleetSpecError("spec", f"invalid JSON: {e}") from e
        return cls.from_dict(raw)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "command": list(self.command),
            "priority": self.priority, "min_np": self.min_np,
            "max_np": self.max_np, "max_restarts": self.max_restarts,
        }
        if self.env:
            out["env"] = dict(self.env)
        if self.restart_window:
            out["restart_window"] = self.restart_window
        if self.drain_grace is not None:
            out["drain_grace"] = self.drain_grace
        if self.autoscale is not None:
            out["autoscale"] = dict(self.autoscale)
        if self.tenant is not None:
            out["tenant"] = self.tenant
        return out

    @property
    def tenant_key(self) -> str:
        """Admission-control key: the declared tenant, else the shared
        ``default`` bucket."""
        return self.tenant if self.tenant is not None else "default"


class Job:
    """One job's arbiter-side record: spec + state + allocation +
    accounting.  NOT internally locked — every mutation happens under
    the owning arbiter's ``_lock`` (see FleetArbiter)."""

    def __init__(self, spec: JobSpec, submit_seq: int):
        self.spec = spec
        self.submit_seq = submit_seq
        self.state = PENDING
        self.reason = ""
        self.submit_t = clock.monotonic()
        self.start_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.queue_wait_s: Optional[float] = None
        # host → slots granted by the arbiter (the handle may report a
        # smaller live view after an external reclaim; _reap adopts it)
        self.allocation: Dict[str, int] = {}
        self.handle = None  # runner handle, set at start
        self.exit_code: Optional[int] = None
        self.preemptions = 0     # arbiter-initiated planned shrinks
        self.charged_restarts = 0  # budget-charged relaunches observed
        # pre-crash counter recovered from state.json: runner handles
        # count from zero each arbiter incarnation, so _reap reports
        # restarts_base + handle.charged_restarts
        self.restarts_base = 0
        # pending planned shrink: grace deadline — expiry escalates to
        # a charged restart via handle.escalate()
        self.shrink_deadline: Optional[float] = None
        self.shrink_started_t: Optional[float] = None
        self.shrink_escalated = False
        self.cancelled = False
        self.unschedulable_reported = False
        self.aged_reported = False   # starvation-guard boost announced
        self.quota_reported = False  # parked at the tenant ranks cap
        # latest fleet health summary (fleet/health.py), pulled by the
        # arbiter each tick; None until the job publishes one
        self.health: Optional[Dict[str, Any]] = None

    @property
    def name(self) -> str:
        return self.spec.name

    def to(self, state: str, reason: str = "") -> None:
        """Validated lifecycle transition; an illegal edge is an
        arbiter bug and raises."""
        if state == self.state:
            return
        if state not in _TRANSITIONS[self.state]:
            raise RuntimeError(
                f"job {self.name}: illegal transition "
                f"{self.state} → {state}")
        self.state = state
        if reason:
            self.reason = reason
        if state == RUNNING and self.start_t is None:
            self.start_t = clock.monotonic()
            self.queue_wait_s = self.start_t - self.submit_t
        if state in (DONE, FAILED):
            self.finish_t = clock.monotonic()

    @property
    def terminal(self) -> bool:
        return self.state in (DONE, FAILED)

    def info(self) -> Dict[str, Any]:
        """state.json / /debug row (deterministic key order via
        json.dumps(sort_keys=True) downstream)."""
        h = self.handle
        out = {
            "name": self.name,
            # the full spec rides along so a restarted arbiter can
            # resubmit non-terminal jobs from state.json alone
            # (FleetArbiter.recover)
            "spec": self.spec.to_dict(),
            "state": self.state,
            "priority": self.spec.priority,
            "min_np": self.spec.min_np,
            "max_np": self.spec.max_np,
            "allocation": dict(self.allocation),
            "np": h.current_np() if h is not None else 0,
            "reason": self.reason or None,
            "exit_code": self.exit_code,
            "preemptions": self.preemptions,
            "charged_restarts": self.charged_restarts,
            "queue_wait_s": (round(self.queue_wait_s, 6)
                             if self.queue_wait_s is not None else None),
            "health": self.health,
        }
        return out


# ---------------------------------------------------------------------------
# job-scoped KV prefixing
# ---------------------------------------------------------------------------

class _PrefixStr:
    """String-tier prefix wrapper (set/get/try_get/delete)."""

    def __init__(self, client, prefix: str):
        self._kv = client
        self._p = prefix.rstrip("/") + "/"

    def _k(self, key: str) -> str:
        return self._p + key

    def key_value_set(self, key, value):
        return self._kv.key_value_set(self._k(key), value)

    def blocking_key_value_get(self, key, timeout_ms):
        return self._kv.blocking_key_value_get(self._k(key), timeout_ms)

    def key_value_try_get(self, key):
        return self._kv.key_value_try_get(self._k(key))

    def key_value_delete(self, key):
        return self._kv.key_value_delete(self._k(key))


class _PrefixDir(_PrefixStr):
    """Adds the directory tier: results are re-rooted so callers see
    their own namespace, never the prefix."""

    def key_value_dir_get(self, prefix):
        full = self._k(prefix)
        return [(k[len(self._p):], v)
                for k, v in self._kv.key_value_dir_get(full)]


class _PrefixBytes(_PrefixDir):
    def key_value_set_bytes(self, key, value):
        return self._kv.key_value_set_bytes(self._k(key), value)

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        return self._kv.blocking_key_value_get_bytes(
            self._k(key), timeout_ms)

    def key_value_try_get_bytes(self, key):
        return self._kv.key_value_try_get_bytes(self._k(key))


def prefixed_client(client, job_name: str):
    """Wrap a coordination-KV client so every key lives under
    ``fleet/<job_name>/``.  The wrapper exposes exactly the capability
    tiers the inner client has (probed, like the drain coordinator's
    dir_get fallback), so feature detection downstream stays truthful.
    """
    prefix = f"fleet/{job_name}"
    if hasattr(client, "key_value_set_bytes"):
        return _PrefixBytes(client, prefix)
    if hasattr(client, "key_value_dir_get"):
        return _PrefixDir(client, prefix)
    return _PrefixStr(client, prefix)
