"""Pallas TPU kernels for data-plane hot ops.

TPU analog of the reference's hand-written CUDA kernels
(``horovod/common/ops/cuda/cuda_kernels.cu``) — see
:mod:`horovod_tpu.ops.pallas_ops`.
"""

from .pallas_ops import (  # noqa: F401
    QBLOCK,
    dequantize_int8_blocks,
    fused_scale_cast,
    quantize_int8_blocks,
)
from .ring import ring_allgather_2d, ring_allreduce  # noqa: F401

__all__ = [
    "QBLOCK",
    "fused_scale_cast",
    "quantize_int8_blocks",
    "dequantize_int8_blocks",
    "ring_allreduce",
    "ring_allgather_2d",
]
