"""hvtpufleet — operator CLI for the hvtpu.fleet arbiter.

Four subcommands against one fleet directory (``--fleet-dir`` /
``HVTPU_FLEET_DIR``):

- ``serve``   run a FleetArbiter over a discovery script, ticking until
  interrupted (or ``--until-idle``).
- ``submit``  validate a job-spec JSON CLIENT-SIDE (malformed specs
  exit 2 naming the first bad field — nothing reaches the arbiter) and
  drop it in the submit spool.
- ``list``    print the arbiter's last published ``state.json``.
- ``cancel``  drop a cancel marker for a named job.

The transport is the repo's notice-file idiom: ``<fleet_dir>/submit/``
and ``<fleet_dir>/cancel/`` spools consumed by the arbiter tick, and an
atomically-replaced ``state.json`` published back.  No daemon socket,
works over any shared filesystem, and the simulator exercises the same
code paths without a network.
"""
