"""Sync-path stall watchdog (comm/stall.py).

Parity: ``horovod/common/stall_inspector.cc`` — the reference warns
after STALL_CHECK_TIME naming the tensors and missing ranks, and shuts
down after STALL_SHUTDOWN_TIME.  Unit tests drive the inspector over a
fake KV client; the integration tests launch 2 REAL processes where
one rank skips (or diverges from) a collective — the exact deadlock
SURVEY §5.2 calls this subsystem essential for — and assert the other
rank aborts with a named diagnosis instead of hanging forever.
"""

import logging
import os
import threading
import time

import pytest

import horovod_tpu
from horovod_tpu.core.exceptions import HorovodInternalError
from horovod_tpu.comm.stall import (
    AmortizedStallInspector,
    SyncStallInspector,
)
from horovod_tpu.runner import run

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


class FakeKV:
    """Dict-backed stand-in for the coordination-service client,
    including the directory get the fast path uses."""

    def __init__(self):
        self.d = {}
        self.lock = threading.Lock()

    def key_value_set(self, k, v):
        with self.lock:
            self.d[k] = v

    def key_value_try_get(self, k):
        with self.lock:
            if k not in self.d:
                raise KeyError(k)
            return self.d[k]

    def key_value_dir_get(self, prefix):
        with self.lock:
            return [(k, v) for k, v in self.d.items()
                    if k.startswith(prefix)]

    def key_value_delete(self, k):
        with self.lock:
            self.d.pop(k, None)


class FakeKVNoDir(FakeKV):
    """An older client without dir-get: exercises the per-rank
    try_get fallback branch."""

    key_value_dir_get = None


@pytest.fixture(params=[FakeKV, FakeKVNoDir],
                ids=["dir-get", "try-get-fallback"])
def kv(request):
    """Both client shapes: the one-RPC dir-get fast path and the
    per-rank try_get fallback must behave identically."""
    return request.param()


class TestInspectorUnit:
    def test_completes_when_all_marks_present(self, kv):
        # peer (rank 1) already posted its mark for seq 0
        kv.key_value_set("hvtstall/1/0/0/1", "allreduce:x")
        insp = SyncStallInspector(kv, rank=0, warn_s=60, abort_s=0,
                                  generation=1)
        insp.rendezvous(0, [0, 1], "allreduce:x")  # returns, no raise
        assert "hvtstall/1/0/0/0" in kv.d  # own mark posted

    def test_abort_names_missing_ranks(self, kv):
        insp = SyncStallInspector(kv, rank=0, warn_s=0.05, abort_s=0.2,
                                  generation=1)
        t0 = time.monotonic()
        with pytest.raises(HorovodInternalError) as ei:
            insp.rendezvous(0, [0, 1, 2], "allreduce:y")
        assert time.monotonic() - t0 < 5.0  # bounded, not a hang
        msg = str(ei.value)
        assert "allreduce:y" in msg
        assert "[1, 2]" in msg  # the missing ranks, by name

    def test_descriptor_mismatch_raises_immediately(self, kv):
        kv.key_value_set("hvtstall/1/0/0/1", "broadcast:z")
        insp = SyncStallInspector(kv, rank=0, warn_s=60, abort_s=0,
                                  generation=1)
        t0 = time.monotonic()
        with pytest.raises(HorovodInternalError, match="diverged"):
            insp.rendezvous(0, [0, 1], "allreduce:z")
        assert time.monotonic() - t0 < 1.0  # no deadline needed

    def test_warn_then_recover(self, kv, caplog):
        insp = SyncStallInspector(kv, rank=0, warn_s=0.05, abort_s=0,
                                  generation=1)

        def late_peer():
            time.sleep(0.3)
            kv.key_value_set("hvtstall/1/0/0/1", "op")

        t = threading.Thread(target=late_peer)
        t.start()
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            insp.rendezvous(0, [0, 1], "op")
        t.join()
        stalls = [r for r in caplog.records
                  if "stalled collective" in r.getMessage()]
        assert stalls and "[1]" in stalls[0].getMessage()

    def test_rolling_cleanup_keeps_kv_bounded(self, kv):
        insp = SyncStallInspector(kv, rank=0, warn_s=60, abort_s=0,
                                  generation=1)
        for seq in range(3):
            kv.key_value_set(f"hvtstall/1/0/{seq}/1", "op")
            insp.rendezvous(0, [0, 1], "op")
        own = [k for k in kv.d if k.endswith("/0")]
        # only the newest own mark survives (seq 2)
        assert own == ["hvtstall/1/0/2/0"]

    def test_generation_namespacing_ignores_stale_marks(self, kv):
        # a PREVIOUS session's mark with a different descriptor must
        # not trip the mismatch check after re-init
        kv.key_value_set("hvtstall/1/0/0/1", "old-op")
        kv.key_value_set("hvtstall/2/0/0/1", "new-op")
        insp = SyncStallInspector(kv, rank=0, warn_s=60, abort_s=0,
                                  generation=2)
        insp.rendezvous(0, [0, 1], "new-op")


class _NeverReady:
    """Stands in for a jax.Array whose collective never completes."""

    def is_ready(self):
        return False


class _Ready:
    def is_ready(self):
        return True


class TestAmortizedInspectorUnit:
    """The default mode: local bookkeeping + background heartbeat.
    Per-op cost must be RPC-free; detection happens within a beat."""

    def _make(self, kv, rank, warn_s=0.05, abort_s=0.0, hb=0.03):
        return AmortizedStallInspector(
            kv, rank, warn_s=warn_s, abort_s=abort_s,
            heartbeat_s=hb, generation=1)

    def test_healthy_path_stays_clean(self):
        kv = FakeKV()
        a, b = self._make(kv, 0), self._make(kv, 1)
        try:
            for i in range(5):
                a.pre_op(0, [0, 1], f"allreduce:t{i}")
                a.wait_ready(0, _Ready())
                b.pre_op(0, [0, 1], f"allreduce:t{i}")
                b.wait_ready(0, _Ready())
            time.sleep(0.2)  # several beats
            assert a.failure is None and b.failure is None
        finally:
            a.stop(); b.stop()

    def test_pre_op_is_rpc_free(self):
        """The hot path must not touch the KV: 10k ops through a KV
        whose set/get explode must neither fail nor take RPC time."""

        class ExplodingKV(FakeKV):
            def key_value_set(self, k, v):
                raise AssertionError("hot path hit the KV")

            key_value_dir_get = property(
                lambda self: (_ for _ in ()).throw(AssertionError))

        insp = AmortizedStallInspector(
            ExplodingKV(), 0, warn_s=60, abort_s=0,
            heartbeat_s=30.0, generation=1)  # beat never fires
        try:
            t0 = time.monotonic()
            for i in range(10_000):
                insp.pre_op(0, [0, 1], "allreduce:x")
                insp.wait_ready(0, _Ready())
            dt = time.monotonic() - t0
            # ~1 µs/op bookkeeping; 50 ms budget leaves 100x headroom
            assert dt < 0.5, f"hot path too slow: {dt:.3f}s / 10k ops"
        finally:
            insp.stop()

    def test_mismatch_diagnosed_within_a_beat(self):
        kv = FakeKV()
        a, b = self._make(kv, 0), self._make(kv, 1)
        try:
            a.pre_op(0, [0, 1], "allreduce:grad:(2,):float32")
            b.pre_op(0, [0, 1], "broadcast:weights:(2,):float32")
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and not (
                    a.failure and b.failure):
                time.sleep(0.02)
            for insp, mine, theirs in (
                    (a, "allreduce:grad", "broadcast:weights"),
                    (b, "broadcast:weights", "allreduce:grad")):
                msg = insp.failure or ""
                assert "diverged" in msg
                # BOTH tensor names appear in the diagnosis
                assert mine in msg and theirs in msg
        finally:
            a.stop(); b.stop()

    def test_stall_abort_names_missing_ranks(self):
        kv = FakeKV()
        a = self._make(kv, 0, warn_s=0.05, abort_s=0.25)
        b = self._make(kv, 1, warn_s=0.05, abort_s=0.25)  # posts beats,
        try:                                              # runs no ops
            a.pre_op(0, [0, 1], "allreduce:loss:(4,):float32")
            with pytest.raises(HorovodInternalError) as ei:
                a.wait_ready(0, _NeverReady())
            msg = str(ei.value)
            assert "stalled collective" in msg
            assert "allreduce:loss" in msg
            assert "[1]" in msg  # the absent rank, by name
        finally:
            a.stop(); b.stop()

    def test_wait_ready_raises_after_peer_failure(self):
        """A rank blocked in a healthy-looking wait must still abort
        when a PEER latches a failure (shutdown-on-stall semantics)."""
        kv = FakeKV()
        a = self._make(kv, 0, hb=0.03)
        b = self._make(kv, 1, hb=0.03)
        try:
            with a._lock:
                a.failure = "synthetic failure on rank 0"
            with pytest.raises(HorovodInternalError, match="rank 0"):
                deadline = time.monotonic() + 2.0
                while time.monotonic() < deadline:
                    b.pre_op(0, [0, 1], "allreduce:x")
                    b.wait_ready(0, _Ready())
                    time.sleep(0.02)
                pytest.fail("peer failure never propagated")
        finally:
            a.stop(); b.stop()

    def test_dead_peer_mid_collective_detected_via_staleness(self):
        """A peer that posts a caught-up heartbeat and THEN dies (mid
        wire-exchange) must still be diagnosed: its beat number stops
        advancing, so staleness marks it absent even though its last
        snapshot showed seq parity."""
        kv = FakeKV()
        a = AmortizedStallInspector(
            kv, 0, warn_s=0.1, abort_s=0.6, heartbeat_s=0.03,
            generation=1, stale_s=0.2)
        b = AmortizedStallInspector(
            kv, 1, warn_s=0.1, abort_s=0.6, heartbeat_s=0.03,
            generation=1, stale_s=0.2)
        try:
            # both ranks dispatch the same op (seq parity)...
            a.pre_op(0, [0, 1], "allreduce:w:(8,):float32")
            b.pre_op(0, [0, 1], "allreduce:w:(8,):float32")
            time.sleep(0.1)  # both post caught-up beats
            # ...then rank 1 dies mid-collective: beats stop, but its
            # last posted snapshot stays in the KV forever
            b._stopped.set()
            with pytest.raises(HorovodInternalError) as ei:
                a.wait_ready(0, _NeverReady())
            msg = str(ei.value)
            assert "stalled collective" in msg and "[1]" in msg
        finally:
            a.stop(); b.stop()

    def test_rearm_names_outer_op_and_keeps_its_clock(self):
        """After a nested negotiation clears the in-flight marker, the
        outer wait re-arms under the OUTER op's descriptor and its
        original start time — not the nested op's."""
        kv = FakeKV()
        insp = AmortizedStallInspector(
            kv, 0, warn_s=60, abort_s=0, heartbeat_s=30.0, generation=1)
        try:
            outer = insp.pre_op(0, [0, 1], "alltoall:x:(4,):float32")
            t_outer = insp._tracks["0"].t0
            time.sleep(0.02)
            insp.pre_op(0, [0, 1], "allgather:splits:(2,):int32")
            insp.wait_ready(0, _Ready())  # nested finish clears marker
            assert insp._tracks["0"].inflight is None

            # outer finish: briefly pending, then ready
            class _ReadyAfter:
                n = 0

                def is_ready(self):
                    self.n += 1
                    if self.n == 1:
                        tr = insp._tracks["0"]
                        assert tr.inflight == "alltoall:x:(4,):float32"
                        assert tr.t0 == t_outer
                    return self.n > 1

            insp.wait_ready(0, _ReadyAfter(), outer)
            assert insp._tracks["0"].inflight is None
        finally:
            insp.stop()

    def test_slow_collective_everyone_present_no_warn(self, caplog):
        """Both ranks dispatched the op (seq caught up): a long wait is
        a slow collective, not a stall — no warning."""
        kv = FakeKV()
        a = self._make(kv, 0, warn_s=0.05, abort_s=0.0)
        b = self._make(kv, 1, warn_s=0.05, abort_s=0.0)
        try:
            a.pre_op(0, [0, 1], "allreduce:big")
            b.pre_op(0, [0, 1], "allreduce:big")
            with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
                time.sleep(0.3)
            assert a.failure is None and b.failure is None
            assert not [r for r in caplog.records
                        if "stalled" in r.getMessage()]
        finally:
            a.stop(); b.stop()


pytestmark_integration = pytest.mark.multiprocess


@pytest.mark.multiprocess
def test_skipped_collective_aborts_cleanly_2proc():
    """Rank 1 skips a collective rank 0 enters: rank 0 must diagnose
    and abort within the stall shutdown deadline — not hang."""

    def body():
        import time as _t

        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.core.exceptions import HorovodInternalError

        hvt.init()
        r = hvt.rank()
        # one successful collective first: the watchdog must not
        # perturb the healthy path
        ok = float(hvt.allreduce(jnp.ones(()), op=hvt.Sum))
        assert ok == 2.0
        if r == 0:
            t0 = _t.monotonic()
            try:
                hvt.allreduce(jnp.ones((4,)), op=hvt.Sum)
            except HorovodInternalError as e:
                waited = _t.monotonic() - t0
                return ("aborted", waited, str(e))
            return ("hung-or-succeeded", None, None)
        _t.sleep(8)  # never calls the collective
        return ("skipped", None, None)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_STALL_CHECK_TIME_SECONDS": "1",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "3",
        }, start_timeout=300.0, timeout=600.0)
    by_rank = dict(zip(("r0", "r1"), results))
    status, waited, msg = results[0]
    assert status == "aborted", by_rank
    assert waited < 8.0
    assert "stalled collective" in msg and "allreduce" in msg
    assert "[1]" in msg  # names the absent rank
    assert results[1][0] == "skipped"


@pytest.mark.multiprocess
def test_diverged_collectives_diagnosed_2proc():
    """Ranks entering DIFFERENT collectives at the same point must get
    the mismatch diagnosis within one heartbeat (amortized mode: the
    doomed op may dispatch — even complete — but the very next
    heartbeat latches the divergence and the job aborts with both op
    names instead of silently desyncing)."""

    def body():
        import time as _t

        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.core.exceptions import HorovodInternalError

        hvt.init()
        r = hvt.rank()
        try:
            # the divergence: same step, different collectives
            if r == 0:
                hvt.allreduce(jnp.ones((2,)), op=hvt.Sum, name="grads")
            else:
                hvt.broadcast(jnp.ones((2,)), root_rank=0, name="weights")
            # a real training loop keeps stepping — the watchdog must
            # kill it within ~a heartbeat, not let it run corrupted
            deadline = _t.monotonic() + 8.0
            while _t.monotonic() < deadline:
                hvt.allreduce(jnp.ones(()), op=hvt.Sum)
                _t.sleep(0.1)
        except HorovodInternalError as e:
            return ("mismatch", str(e))
        return ("no-error", None)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_STALL_CHECK_TIME_SECONDS": "1",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "10",
            "HVTPU_STALL_HEARTBEAT_SECONDS": "0.2",
        }, start_timeout=300.0, timeout=600.0)
    assert any(s == "mismatch" for s, _ in results), results
    for s, msg in results:
        if s == "mismatch":
            assert "diverged" in msg
            # the diagnosis names the diverged ops by tensor name
            assert "grads" in msg and "weights" in msg, msg


@pytest.mark.multiprocess
def test_diverged_strict_mode_immediate_2proc():
    """HVTPU_STALL_CHECK_MODE=strict restores the pre-dispatch
    rendezvous: a mismatched collective is diagnosed BEFORE anything
    dispatches, on the first offending op."""

    def body():
        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.core.exceptions import HorovodInternalError

        hvt.init()
        r = hvt.rank()
        try:
            if r == 0:
                hvt.allreduce(jnp.ones((2,)), op=hvt.Sum)
            else:
                hvt.broadcast(jnp.ones((2,)), root_rank=0)
        except HorovodInternalError as e:
            return ("mismatch", str(e))
        return ("no-error", None)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_STALL_CHECK_MODE": "strict",
            "HVTPU_STALL_CHECK_TIME_SECONDS": "1",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "10",
        }, start_timeout=300.0, timeout=600.0)
    # at least the slower-arriving rank sees the peer's conflicting
    # mark; with both marks posted, typically both do
    assert any(s == "mismatch" for s, _ in results), results
    for s, msg in results:
        if s == "mismatch":
            assert "diverged" in msg


class TestStallGuardUnit:
    def test_passthrough_before_init_and_at_world_1(self):
        import jax.numpy as jnp

        from horovod_tpu.comm.stall import stall_guard

        calls = []

        @stall_guard(name="t")
        def step(x):
            calls.append(1)
            return x + 1

        # single-process hvt: guard must be a plain passthrough
        horovod_tpu.init()
        try:
            out = step(jnp.zeros(()))
            assert float(out) == 1.0 and calls == [1]
        finally:
            horovod_tpu.shutdown()

    def test_guard_marks_and_diverged_names(self):
        """Two guards with different names on the same channel set:
        the heartbeat diagnoses ranks running different step fns."""
        kv = FakeKV()
        a = AmortizedStallInspector(kv, 0, warn_s=60, abort_s=0,
                                    heartbeat_s=0.03, generation=1)
        b = AmortizedStallInspector(kv, 1, warn_s=60, abort_s=0,
                                    heartbeat_s=0.03, generation=1)
        try:
            a.pre_op("jit.0", [0, 1], "jit_step:train")
            b.pre_op("jit.0", [0, 1], "jit_step:evaluate")
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and not a.failure:
                time.sleep(0.02)
            assert a.failure and "jit_step:train" in a.failure
            assert "jit_step:evaluate" in a.failure
        finally:
            a.stop(); b.stop()

    def test_stopped_ranks_tombstone_propagates_failure(self):
        """An aborting rank usually stops BEFORE its next scheduled
        beat: its goodbye tombstone must carry the latched diagnosis,
        or the peers never learn it — they'd hang in their next
        collective and die on the torn-down transport instead."""
        kv = FakeKV()
        a = AmortizedStallInspector(kv, 0, warn_s=60, abort_s=0,
                                    heartbeat_s=5.0, generation=1)
        b = AmortizedStallInspector(kv, 1, warn_s=60, abort_s=0,
                                    heartbeat_s=0.03, generation=1)
        try:
            # rank 0 latches a divergence and stops immediately — its
            # 5s heartbeat never gets to post the failure in a beat
            with a._lock:
                a.failure = ("collective mismatch at process set 0 op "
                             "#3: ... diverged ...")
            a.stop()
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and not b.failure:
                time.sleep(0.02)
            assert b.failure and "rank 0 aborted" in b.failure
            assert "diverged" in b.failure
        finally:
            a.stop(); b.stop()

    def test_clean_exit_not_blamed(self):
        """A rank whose inspector stopped CLEANLY (goodbye tombstone)
        is never blamed for a stall, even with a marker still armed."""
        kv = FakeKV()
        a = AmortizedStallInspector(
            kv, 0, warn_s=0.05, abort_s=0.3, heartbeat_s=0.03,
            generation=1, stale_s=0.15)
        b = AmortizedStallInspector(
            kv, 1, warn_s=0.05, abort_s=0.3, heartbeat_s=0.03,
            generation=1, stale_s=0.15)
        try:
            # both step once (block=False style: marker stays armed)
            a.pre_op("jit.0", [0, 1], "jit_step:s")
            b.pre_op("jit.0", [0, 1], "jit_step:s")
            time.sleep(0.1)
            b.stop()  # clean exit posts the tombstone
            time.sleep(0.5)  # well past warn+abort+stale deadlines
            assert a.failure is None, a.failure
        finally:
            a.stop(); b.stop()


@pytest.mark.multiprocess
def test_stall_guard_jit_plane_2proc():
    """The VERDICT-r4 gap: a pod-shape jitted training loop where one
    process stops dispatching.  The guarded survivor must abort with a
    named diagnosis instead of hanging inside the XLA collective."""

    def body():
        import time as _t

        import jax
        import jax.numpy as jnp

        import horovod_tpu as hvt
        from horovod_tpu.core.exceptions import HorovodInternalError

        hvt.init()
        r = hvt.rank()
        mesh = hvt.world_mesh()
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        # a REAL cross-process collective inside the step:
        def make_step():
            from jax.experimental.shard_map import shard_map

            @hvt.stall_guard(name="train")
            @jax.jit
            @partial(shard_map, mesh=mesh, in_specs=P("world"),
                     out_specs=P(), check_rep=False)
            def train(x):
                return jax.lax.psum(x.sum(), "world")

            return train

        train = make_step()
        xs = jax.device_put(
            jnp.ones((2,)),
            NamedSharding(mesh, P("world")))
        t0 = _t.monotonic()
        try:
            for i in range(100):
                if r == 1 and i == 3:
                    _t.sleep(10)  # stops stepping mid-loop
                    return ("stopped", None)
                float(train(xs))
        except HorovodInternalError as e:
            return ("aborted", str(e))
        return ("finished", None)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_STALL_CHECK_TIME_SECONDS": "1",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "3",
            "HVTPU_STALL_HEARTBEAT_SECONDS": "0.2",
        }, start_timeout=300.0, timeout=600.0)
    status0, msg0 = results[0]
    assert status0 == "aborted", results
    assert "jit_step:train" in msg0 and "[1]" in msg0
    assert results[1][0] == "stopped"


@pytest.mark.multiprocess
def test_stall_guard_strict_mode_2proc():
    """stall_guard under HVTPU_STALL_CHECK_MODE=strict: each step is a
    pre-dispatch rendezvous — a rank that stops stepping aborts the
    survivor at the step boundary BEFORE it dispatches the doomed
    step."""

    def body():
        import time as _t
        from functools import partial

        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        import horovod_tpu as hvt
        from horovod_tpu.core.exceptions import HorovodInternalError

        hvt.init()
        r = hvt.rank()
        mesh = hvt.world_mesh()

        @hvt.stall_guard(name="strict_train")
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=P("world"),
                 out_specs=P(), check_rep=False)
        def train(x):
            return jax.lax.psum(x.sum(), "world")

        xs = jax.device_put(
            jnp.ones((2,)), NamedSharding(mesh, P("world")))
        try:
            for i in range(50):
                if r == 1 and i == 2:
                    _t.sleep(8)
                    return ("stopped", None)
                float(train(xs))
        except HorovodInternalError as e:
            return ("aborted", str(e))
        return ("finished", None)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_STALL_CHECK_MODE": "strict",
            "HVTPU_STALL_CHECK_TIME_SECONDS": "1",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "3",
        }, start_timeout=300.0, timeout=600.0)
    status0, msg0 = results[0]
    assert status0 == "aborted", results
    # strict mode: the abort happens at the rendezvous, pre-dispatch,
    # with the step and absent rank named
    assert "jit_step:strict_train" in msg0 and "[1]" in msg0
    assert results[1][0] == "stopped"


@pytest.mark.multiprocess
def test_watchdog_transparent_on_healthy_path_2proc():
    """With stall checking at defaults, the full sync op matrix still
    produces correct results (the rendezvous must be semantically
    invisible)."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        a = np.asarray(hvt.allreduce(jnp.full((3,), float(r + 1)),
                                     op=hvt.Sum))
        g = np.asarray(hvt.allgather(jnp.full((r + 1, 2), float(r))))
        b = np.asarray(hvt.broadcast(jnp.full((2,), float(r * 7)),
                                     root_rank=1))
        rs = np.asarray(hvt.reducescatter(jnp.ones((4, 2)), op=hvt.Sum))
        hvt.barrier()
        return (a.tolist(), g.shape[0], b.tolist(), rs.tolist())

    results = run(body, np=2, cpu_devices=1, env=_ENV,
                  start_timeout=300.0)
    for a, g0, b, rs in results:
        assert a == [3.0, 3.0, 3.0]
        assert g0 == 3
        assert b == [7.0, 7.0]
        assert rs == [[2.0, 2.0], [2.0, 2.0]]


class TestPoisonLatch:
    """The poison latch across re-init generations (ISSUE-2 satellite):
    ``poison_exit_status`` must clear (0) ONLY once ``init_generation``
    advances past the poisoning generation, and an elastic job's
    terminal stall abort must feed the driver's recovery loop
    (``RESET_EXIT_CODE``) instead of reading as a crash."""

    @pytest.fixture()
    def latched(self, monkeypatch):
        from horovod_tpu.comm import stall
        from horovod_tpu.core import state as core_state

        st = core_state.global_state()
        insp = AmortizedStallInspector(
            FakeKV(), rank=0, warn_s=10, abort_s=0, heartbeat_s=60,
            generation=st.init_generation)
        monkeypatch.setattr(st, "sync_stall", insp)
        monkeypatch.delenv("HVTPU_ELASTIC", raising=False)
        stall._latch_poison(insp)
        yield stall, st, insp
        insp.stop()
        stall._reset_poison()

    def test_latch_requires_installed_inspector(self):
        from horovod_tpu.comm import stall

        stray = AmortizedStallInspector(
            FakeKV(), rank=0, warn_s=10, abort_s=0, heartbeat_s=60)
        try:
            stall._latch_poison(stray)  # NOT the installed inspector
            assert not stall.poisoned()
        finally:
            stray.stop()
            stall._reset_poison()

    def test_same_generation_is_terminal(self, latched):
        stall, st, insp = latched
        assert stall.poisoned()
        assert stall.poison_exit_status() == 1

    def test_clears_only_past_poisoning_generation(self, latched,
                                                   monkeypatch):
        stall, st, insp = latched
        # re-init into the SAME generation: still terminal
        assert stall.poison_exit_status() == 1
        # generation advances PAST the poisoning one (elastic in-process
        # resync completed): the wedged execution belongs to a dead
        # session — exit clean
        monkeypatch.setattr(st, "init_generation",
                            insp.gen + 1)
        assert stall.poison_exit_status() == 0

    def test_elastic_terminal_stall_requests_reset(self, latched,
                                                   monkeypatch):
        stall, st, insp = latched
        from horovod_tpu.elastic.worker import RESET_EXIT_CODE

        monkeypatch.setenv("HVTPU_ELASTIC", "1")
        assert stall.poison_exit_status() == RESET_EXIT_CODE
        # ...but a completed recovery still wins: clean exit
        monkeypatch.setattr(st, "init_generation", insp.gen + 1)
        assert stall.poison_exit_status() == 0


class TestInflightLeakRegression:
    """PR-20 satellite: an exception inside ``dispatch``/``wait_ready``
    must CLEAR the in-flight marker.  The leak left ``_SetTrack.
    inflight`` armed with the dead op's start time, so the marker aged
    across later healthy ops and the heartbeat eventually diagnosed a
    false stall abort on a perfectly live job."""

    def _make(self, kv, rank, warn_s=0.05, abort_s=0.0, hb=0.03):
        return AmortizedStallInspector(
            kv, rank, warn_s=warn_s, abort_s=abort_s,
            heartbeat_s=hb, generation=1)

    def test_dispatch_error_clears_inflight(self):
        insp = self._make(FakeKV(), 0, warn_s=60, hb=30.0)
        try:
            insp.pre_op(0, [0, 1], "allreduce:x")

            def boom():
                raise ValueError("backend exploded")

            with pytest.raises(ValueError, match="exploded"):
                insp.dispatch(0, boom, ())
            assert insp._tracks["0"].inflight is None
        finally:
            insp.stop()

    def test_wait_ready_error_clears_inflight(self):
        insp = self._make(FakeKV(), 0, warn_s=60, hb=30.0)
        try:
            insp.pre_op(0, [0, 1], "allreduce:y")

            class _Explodes:
                def is_ready(self):
                    raise RuntimeError("torn result")

            with pytest.raises(RuntimeError, match="torn result"):
                insp.wait_ready(0, _Explodes())
            assert insp._tracks["0"].inflight is None
        finally:
            insp.stop()

    def test_failed_attempt_never_becomes_false_stall_abort(self):
        """The observable symptom: after a failed dispatch, an idle-but-
        healthy job must NOT age the stale marker into a stall abort
        naming the innocent peer."""
        kv = FakeKV()
        a = self._make(kv, 0, warn_s=0.05, abort_s=0.25)
        b = self._make(kv, 1, warn_s=0.05, abort_s=0.25)
        try:
            a.pre_op(0, [0, 1], "allreduce:z")

            def boom():
                raise ValueError("attempt died")

            with pytest.raises(ValueError):
                a.dispatch(0, boom, ())
            # well past warn + abort: the cleared marker means no op is
            # in flight, so nothing may latch
            time.sleep(0.6)
            assert a.failure is None, a.failure
        finally:
            a.stop(); b.stop()


class TestWireConsensusUnit:
    """comm/wirefault.py: the abort-and-retry agreement over a fake KV
    — every decision path, plus the no-torn-attempt property."""

    def _wc(self, kv, rank=0, deadline_s=5.0):
        from horovod_tpu.comm import wirefault

        return wirefault.WireConsensus(
            kv, rank, generation=1, hb_prefix="hvtstallhb/1/",
            deadline_s=deadline_s)

    def _hb(self, kv, rank, seq, inflight, beat=0, bye=False, fail=None):
        import json

        kv.key_value_set(
            f"hvtstallhb/1/{rank}/{beat}",
            json.dumps({"bye": bye, "fail": fail,
                        "sets": {"0": {"seq": seq,
                                       "inflight": inflight}}}))

    def test_all_voted_means_retry(self, kv):
        import json

        from horovod_tpu.comm import wirefault

        for r in (1, 2):
            kv.key_value_set(f"hvtwire/1/0/5/0/{r}",
                             json.dumps({"st": "mid", "d": "allreduce:x"}))
        wc = self._wc(kv)
        got = wc.vote_and_decide("0", 5, 0, [0, 1, 2], "allreduce:x",
                                 predispatch=False)
        assert got == wirefault.RETRY
        # own vote rode the KV for the peers' agreement
        assert "hvtwire/1/0/5/0/0" in kv.d

    def test_completed_peer_escalates(self):
        import json

        from horovod_tpu.comm import wirefault

        kv = FakeKV()
        kv.key_value_set("hvtwire/1/0/5/0/1",
                         json.dumps({"st": "pre", "d": "allreduce:x"}))
        # rank 2 never votes: its heartbeat shows it COMPLETED op 5
        # and moved on (seq advanced past) — a retry would deliver a
        # second, different attempt on rank 2
        self._hb(kv, 2, seq=7, inflight=None)
        wc = self._wc(kv)
        got = wc.vote_and_decide("0", 5, 0, [0, 1, 2], "allreduce:x",
                                 predispatch=True)
        assert got == wirefault.ESCALATE

    def test_exited_peer_escalates(self):
        import json

        from horovod_tpu.comm import wirefault

        kv = FakeKV()
        kv.key_value_set("hvtwire/1/0/5/0/1",
                         json.dumps({"st": "pre", "d": "allreduce:x"}))
        self._hb(kv, 2, seq=6, inflight="allreduce:x", bye=True)
        wc = self._wc(kv)
        got = wc.vote_and_decide("0", 5, 0, [0, 1, 2], "allreduce:x",
                                 predispatch=True)
        assert got == wirefault.ESCALATE

    def test_wedged_peers_late_join_retracts_vote(self):
        """Every voter failed PRE-dispatch and the non-voters are
        observably parked inside attempt 0: re-enter it (LATE_JOIN) —
        and the failure vote must flip to ``rejoin`` BEFORE re-entry,
        so a peer failing later can never read a completed vote set."""
        import json

        from horovod_tpu.comm import wirefault

        kv = FakeKV()
        kv.key_value_set("hvtwire/1/0/5/0/1",
                         json.dumps({"st": "pre", "d": "allreduce:x"}))
        self._hb(kv, 2, seq=6, inflight="allreduce:x")
        wc = self._wc(kv)
        got = wc.vote_and_decide("0", 5, 0, [0, 1, 2], "allreduce:x",
                                 predispatch=True)
        assert got == wirefault.LATE_JOIN
        assert json.loads(kv.d["hvtwire/1/0/5/0/0"])["st"] == "rejoin"

    def test_midflight_failure_never_late_joins(self):
        """A failure AFTER bytes hit the wire can only RETRY (all voted)
        or ESCALATE — here the wedged peer never votes, so the deadline
        escalates rather than tearing into the pending attempt."""
        from horovod_tpu.comm import wirefault

        kv = FakeKV()
        self._hb(kv, 2, seq=6, inflight="allreduce:x")
        wc = self._wc(kv, deadline_s=0.3)
        t0 = time.monotonic()
        got = wc.vote_and_decide("0", 5, 0, [0, 2], "allreduce:x",
                                 predispatch=False)
        assert got == wirefault.ESCALATE
        assert time.monotonic() - t0 < 5.0  # bounded by the deadline

    def test_deadline_escalates_on_silent_peer(self):
        from horovod_tpu.comm import wirefault

        kv = FakeKV()  # rank 1: no vote, no heartbeat — nothing to read
        wc = self._wc(kv, deadline_s=0.2)
        got = wc.vote_and_decide("0", 5, 0, [0, 1], "allreduce:x",
                                 predispatch=True)
        assert got == wirefault.ESCALATE

    def test_rejoin_vote_never_licenses_next_attempt(self):
        """The no-torn-result property: with a late-joiner back INSIDE
        attempt 0 (rejoin vote), a subsequently-failing peer must never
        decide RETRY — the late-joiner would wedge in attempt 0 while
        others tear off into attempt 1."""
        import json

        from horovod_tpu.comm import wirefault

        kv = FakeKV()
        kv.key_value_set("hvtwire/1/0/5/0/1",
                         json.dumps({"st": "rejoin", "d": "allreduce:x"}))
        wc = self._wc(kv, deadline_s=0.3)
        # pre-dispatch failure: join the pending attempt instead
        assert wc.vote_and_decide(
            "0", 5, 0, [0, 1], "allreduce:x",
            predispatch=True) == wirefault.LATE_JOIN
        # mid-flight failure: cannot join — escalate, never RETRY
        assert wc.vote_and_decide(
            "0", 5, 0, [0, 1], "allreduce:x",
            predispatch=False) == wirefault.ESCALATE

    def test_cleanup_deletes_only_own_votes(self):
        import json

        kv = FakeKV()
        kv.key_value_set("hvtwire/1/0/5/0/1", json.dumps({"st": "mid"}))
        wc = self._wc(kv)
        wc.vote_and_decide("0", 5, 0, [0, 1], "op", predispatch=False)
        wc.cleanup("0", 5, attempts=1)
        assert "hvtwire/1/0/5/0/0" not in kv.d
        assert "hvtwire/1/0/5/0/1" in kv.d  # the peer deletes its own

    def test_attempt_tag_namespaces_are_disjoint(self):
        from horovod_tpu.native.wire import attempt_tag, split_attempt

        assert attempt_tag("hvt/allreduce/x", 0) == "hvt/allreduce/x"
        tagged = attempt_tag("hvt/allreduce/x", 3)
        assert tagged != "hvt/allreduce/x"
        assert split_attempt(tagged) == ("hvt/allreduce/x", 3)
        assert split_attempt("hvt/allreduce/x") == ("hvt/allreduce/x", 0)
        # attempts never collide with each other or with attempt 0
        assert len({attempt_tag("k", a) for a in range(5)}) == 5


class TestWireRetryLoop:
    """The module-level ``dispatch`` retry loop end-to-end in-process:
    an injected ``wire.send`` drop, a real consensus round over the
    fake KV, and the reissued attempt delivering the result."""

    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        from horovod_tpu.core import faults

        yield
        faults.uninstall()

    def _harness(self, kv, members=(0,)):
        from types import SimpleNamespace

        insp = AmortizedStallInspector(
            kv, 0, warn_s=60, abort_s=0, heartbeat_s=0.05, generation=1)
        st = SimpleNamespace(sync_stall=insp)
        ps = SimpleNamespace(size=2, process_set_id=0)
        insp.pre_op(0, list(members), "allreduce:r:(2,):float32")
        return insp, st, ps

    def test_consensus_retry_delivers_result(self, monkeypatch):
        from horovod_tpu.comm import stall as stall_mod
        from horovod_tpu.core import faults
        from horovod_tpu.obs import metrics as obs_metrics

        monkeypatch.setenv("HVTPU_WIRE_RETRIES", "2")
        monkeypatch.setenv("HVTPU_WIRE_RETRY_BACKOFF_S", "0.01")
        faults.install("wire.send:drop@times=1", rank=0)
        kv = FakeKV()
        insp, st, ps = self._harness(kv)
        before = obs_metrics.counter(
            "hvtpu_collective_retries_total").value()
        try:
            out = stall_mod.dispatch(st, ps, lambda: 42, (),
                                     desc="allreduce:r:(2,):float32")
            assert out == 42
            assert obs_metrics.counter(
                "hvtpu_collective_retries_total").value() == before + 1
            # delivered: the rank's own votes were cleaned up
            assert not [k for k in kv.d if k.startswith("hvtwire/")]
            # and the completion wait leaves no stale marker behind
            insp.wait_ready(0, out)
            assert insp._tracks["0"].inflight is None
        finally:
            insp.stop()

    def test_retries_disabled_is_failfast(self, monkeypatch):
        """Default budget (0): the injected wire fault surfaces as the
        pre-existing HorovodInternalError with zero consensus traffic
        — the opt-out path is byte-for-byte the old behavior."""
        from horovod_tpu.comm import stall as stall_mod
        from horovod_tpu.core import faults

        monkeypatch.delenv("HVTPU_WIRE_RETRIES", raising=False)
        faults.install("wire.send:drop@times=1", rank=0)
        kv = FakeKV()
        insp, st, ps = self._harness(kv)
        try:
            with pytest.raises(HorovodInternalError,
                               match="transport failure"):
                stall_mod.dispatch(st, ps, lambda: 42, ())
            assert not [k for k in kv.d if k.startswith("hvtwire/")]
        finally:
            insp.stop()

    def test_budget_exhaustion_escalates(self, monkeypatch):
        from horovod_tpu.comm import stall as stall_mod
        from horovod_tpu.core import faults

        monkeypatch.setenv("HVTPU_WIRE_RETRIES", "2")
        monkeypatch.setenv("HVTPU_WIRE_RETRY_BACKOFF_S", "0.01")
        faults.install("wire.send:drop", rank=0)  # unlimited drops
        insp, st, ps = self._harness(FakeKV())
        try:
            with pytest.raises(HorovodInternalError,
                               match="transport failure"):
                stall_mod.dispatch(st, ps, lambda: 42, ())
        finally:
            insp.stop()

    def test_non_transport_error_is_not_retried(self, monkeypatch):
        from horovod_tpu.comm import stall as stall_mod

        monkeypatch.setenv("HVTPU_WIRE_RETRIES", "3")

        def boom():
            raise ValueError("a real bug, not the wire")

        insp, st, ps = self._harness(FakeKV())
        try:
            with pytest.raises(ValueError, match="real bug"):
                stall_mod.dispatch(st, ps, boom, ())
        finally:
            insp.stop()


@pytest.mark.multiprocess
def test_wire_drop_retry_bitwise_identical_2proc():
    """PR-20 acceptance: rank 0's allreduce dies on an injected
    ``wire.send`` drop with retries armed.  The abort consensus sees
    rank 1 parked inside the pending attempt (late join), the reissued
    dispatch completes it, and the delivered tensor is BITWISE-equal to
    the clean run on both ranks — the job never restarts and never
    consumes bytes from the aborted attempt."""

    def body():
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvt
        from horovod_tpu.core import faults
        from horovod_tpu.obs import metrics as obs_metrics

        hvt.init()
        r = hvt.rank()
        x = jnp.arange(8, dtype=jnp.float32) * (r + 1) + 0.125
        clean = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="clean"))
        before = obs_metrics.counter(
            "hvtpu_collective_retries_total").value()
        # only rank 0's next send dies; rank 1 dispatches and wedges
        # inside the pending collective until the late join lands
        faults.install("wire.send:drop@rank=0,times=1", rank=r)
        faulted = np.asarray(hvt.allreduce(x, op=hvt.Sum, name="clean"))
        faults.uninstall()
        retries = obs_metrics.counter(
            "hvtpu_collective_retries_total").value() - before
        # the job is still healthy: one more collective completes
        ok = float(hvt.allreduce(jnp.ones(()), op=hvt.Sum))
        return (clean.tolist(), faulted.tolist(), retries, ok)

    results = run(
        body, np=2, cpu_devices=1, env={
            **_ENV,
            "HVTPU_WIRE_RETRIES": "2",
            "HVTPU_WIRE_CONSENSUS_S": "30",
            "HVTPU_STALL_HEARTBEAT_SECONDS": "0.2",
            "HVTPU_STALL_CHECK_TIME_SECONDS": "5",
            "HVTPU_STALL_SHUTDOWN_TIME_SECONDS": "60",
        }, start_timeout=300.0, timeout=600.0)
    for clean, faulted, retries, ok in results:
        assert faulted == clean, (faulted, clean)  # bitwise identical
        assert ok == 2.0
    # the faulted rank's reissue was consensus-approved and counted
    assert results[0][2] >= 1, results
