"""TPU-tuned BatchNorm.

Drop-in replacement for ``flax.linen.BatchNorm`` with the same
semantics (running stats, ``axis_name`` cross-replica sync — the
SyncBatchNorm analog of horovod/torch/sync_batch_norm.py), but with the
statistics computed over a FLATTENED (N*H*W, C) view: XLA:TPU lowers
the 2-D column reduce to a fast single-pass kernel, while the
multi-axis (0, 1, 2) spatial reduce flax emits runs an order of
magnitude slower on this hardware (measured ~14x on v5e — it dominated
the ResNet-50 step before this).

Stats accumulate in float32 regardless of compute dtype (same as flax).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class TpuBatchNorm(nn.Module):
    """BatchNorm over the last axis with TPU-fast statistics.

    Matches flax.linen.BatchNorm's interface for the subset the models
    here use: feature axis -1, running stats in a ``batch_stats``
    collection, optional cross-replica ``axis_name``.
    """

    use_running_average: bool = False
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    axis_name: Optional[str] = None
    use_bias: bool = True
    use_scale: bool = True
    bias_init: Callable = nn.initializers.zeros
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average,
        )
        feats = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((feats,), jnp.float32),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((feats,), jnp.float32),
        )

        if use_ra:
            mean, var = ra_mean.value, ra_var.value
        else:
            # TPU-fast statistics: flatten every non-feature axis so the
            # reduce is a plain 2-D column reduction; convert-to-f32
            # fuses into the reduce (one read of x).
            x2 = x.reshape(-1, feats)
            n = x2.shape[0]
            mean = jnp.mean(x2, axis=0, dtype=jnp.float32)
            mean_sq = jnp.mean(
                jnp.square(x2.astype(jnp.float32)), axis=0
            )
            # cross-replica sync (SyncBatchNorm): average the moments,
            # not the variances.  Skipped while initializing — init()
            # runs OUTSIDE shard_map, where the axis name is unbound
            # (and init-time stats are discarded defaults anyway).
            if self.axis_name is not None and not self.is_initializing():
                mean = jax.lax.pmean(mean, self.axis_name)
                mean_sq = jax.lax.pmean(mean_sq, self.axis_name)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                # flax parity: running var uses the biased batch var
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )

        dtype = self.dtype or x.dtype
        inv = jax.lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            scale = self.param(
                "scale", self.scale_init, (feats,), jnp.float32
            )
            inv = inv * scale
        # Fold (mean, inv, bias) into per-channel (a, b) in fp32, then
        # run the big elementwise pass in the compute dtype — keeps the
        # activation traffic at bf16 width (fp32 here would double the
        # step's dominant HBM cost).
        shift = -mean * inv
        if self.use_bias:
            bias = self.param(
                "bias", self.bias_init, (feats,), jnp.float32
            )
            shift = shift + bias
        y = x * inv.astype(dtype) + shift.astype(dtype)
        return y.astype(dtype)
