"""TF/Keras frontend across REAL processes (the `horovodrun -np 2
test_tensorflow.py` analog): cross-process gradient averaging through
DistributedGradientTape and a keras fit that stays in lockstep.
"""

import os

import pytest

import horovod_tpu
from horovod_tpu.runner import run

pytestmark = pytest.mark.multiprocess

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


def test_tf_tape_and_collectives_2proc():
    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        out = {}

        out["sum"] = hvd.allreduce(
            tf.constant([float(r + 1)]), op=hvd.Sum
        ).numpy().tolist()
        out["gather"] = hvd.allgather(
            tf.fill((r + 1, 2), float(r))
        ).numpy().tolist()

        # tape averaging: rank-dependent grads -> identical average
        w = tf.Variable([[float(r + 1)]])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(w * float(10 * (r + 1)))
        dtape = hvd.DistributedGradientTape(tape)
        (g,) = dtape.gradient(loss, [w])
        out["tape_grad"] = g.numpy().ravel().tolist()

        v = tf.Variable([float(r * 100)])
        hvd.broadcast_variables([v], root_rank=1)
        out["bvar"] = v.numpy().tolist()
        return (r, out)

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    for r, out in results:
        assert out["sum"] == [3.0]
        assert out["gather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert out["tape_grad"] == [15.0]  # avg(10, 20)
        assert out["bvar"] == [100.0]


def test_keras_fit_lockstep_2proc():
    def body():
        import numpy as np

        import keras

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        rng = np.random.RandomState(r)  # DIFFERENT data per rank
        x = rng.rand(64, 4).astype(np.float32)
        y = x @ np.arange(4, dtype=np.float32).reshape(4, 1)

        keras.utils.set_random_seed(100 + r)  # different init per rank
        model = keras.Sequential([keras.layers.Dense(1)])
        dopt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.05)
        )
        model.compile(optimizer=dopt, loss="mse")
        model.fit(
            x, y, epochs=2, batch_size=16, verbose=0,
            callbacks=[
                hvd.callbacks.BroadcastGlobalVariablesCallback(0),
                hvd.callbacks.MetricAverageCallback(),
            ],
        )
        return (r, [w.tolist() for w in model.get_weights()])

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    (r0, w0), (r1, w1) = results
    # broadcast + averaged grads keep ranks bit-identical despite
    # different data and different seeds
    assert w0 == w1


def test_tf_graph_mode_fused_broadcast_2proc():
    """Graph-mode (tf.function) broadcast_variables across real
    processes: the fused per-dtype path must deliver rank-0 values to
    every rank inside a traced function."""

    def body():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()
        vs = [tf.Variable(tf.fill((4,), float((r + 1) * (i + 1))))
              for i in range(6)]
        iv = tf.Variable(tf.constant([r, r], tf.int32))

        @tf.function
        def sync():
            hvd.broadcast_variables(vs + [iv], root_rank=0)

        sync()
        # rank 0's values everywhere: (i+1) for the floats, [0, 0] int
        ok_f = all(
            np.allclose(v.numpy(), np.full((4,), float(i + 1)))
            for i, v in enumerate(vs)
        )
        ok_i = iv.numpy().tolist() == [0, 0]

        # graph-mode collective correctness too (allreduce in a trace)
        @tf.function
        def red():
            return hvd.allreduce(tf.constant([float(r + 1)]), op=hvd.Sum)

        s = float(red().numpy()[0])
        return (r, ok_f, ok_i, s)

    results = run(body, np=2, cpu_devices=1, env=_ENV)
    for r, ok_f, ok_i, s in results:
        assert ok_f and ok_i
        assert s == 3.0
