"""Checkpoint/resume tests (SURVEY.md §5.4 — the orbax-style async
rank-0 checkpoint idiom + broadcast fanout)."""

import numpy as np
import pytest

import horovod_tpu


class TestCheckpointer:
    def test_save_restore_roundtrip(self, hvt, tmp_path):
        import jax.numpy as jnp

        ckpt = hvt.Checkpointer(str(tmp_path / "ck"))
        payload = {
            "params": {"w": jnp.arange(6.0).reshape(2, 3),
                       "b": jnp.zeros(3)},
            "step": np.asarray(7),
        }
        ckpt.save(7, payload)
        ckpt.wait()
        assert ckpt.all_steps() == [7]
        out = ckpt.restore()
        np.testing.assert_allclose(
            np.asarray(out["params"]["w"]),
            np.arange(6.0).reshape(2, 3),
        )

    def test_latest_and_specific_step(self, hvt, tmp_path):
        import jax.numpy as jnp

        ckpt = hvt.Checkpointer(str(tmp_path / "ck"))
        for s in (1, 5, 3):
            ckpt.save(s, {"v": jnp.asarray(float(s))})
            ckpt.wait()
        assert ckpt.latest_step() == 5
        assert float(np.asarray(ckpt.restore()["v"])) == 5.0
        assert float(np.asarray(ckpt.restore(step=3)["v"])) == 3.0

    def test_max_to_keep_gc(self, hvt, tmp_path):
        import jax.numpy as jnp

        ckpt = hvt.Checkpointer(str(tmp_path / "ck"), max_to_keep=2)
        for s in range(4):
            ckpt.save(s, {"v": jnp.asarray(float(s))})
            ckpt.wait()
        assert ckpt.all_steps() == [2, 3]

    def test_restore_empty_returns_none(self, hvt, tmp_path):
        ckpt = hvt.Checkpointer(str(tmp_path / "nothing"))
        assert ckpt.restore() is None

    def test_one_shot_helpers(self, hvt, tmp_path):
        import jax.numpy as jnp

        d = str(tmp_path / "ck")
        hvt.save_checkpoint(d, 11, {"x": jnp.ones(2)}).wait()
        out = hvt.restore_checkpoint(d)
        np.testing.assert_allclose(np.asarray(out["x"]), [1.0, 1.0])

    def test_resave_same_step_overwrites(self, hvt, tmp_path):
        """Re-saving to the SAME path must replace the old payload —
        the os.replace-onto-non-empty-directory ENOTEMPTY regression
        (both backends)."""
        import jax.numpy as jnp

        for orbax in (False, True):
            ckpt = hvt.Checkpointer(str(tmp_path / f"ck{int(orbax)}"),
                                    use_orbax=orbax)
            ckpt.save(7, {"v": jnp.asarray(1.0)})
            ckpt.wait()
            ckpt.save(7, {"v": jnp.asarray(2.0)})
            ckpt.wait()
            assert ckpt.all_steps() == [7]
            assert float(np.asarray(ckpt.restore(7)["v"])) == 2.0

    def test_stale_tmp_from_killed_worker_is_cleaned(self, hvt,
                                                     tmp_path):
        """A .tmp leftover from a save killed mid-write must neither
        fail the next save nor leak its stale files into the final
        checkpoint directory."""
        import os

        import jax.numpy as jnp

        d = tmp_path / "ck"
        ckpt = hvt.Checkpointer(str(d), use_orbax=False)
        stale = d / "step_000000000007.tmp"
        stale.mkdir(parents=True)
        (stale / "garbage.pkl").write_text("killed mid-write")
        ckpt.save(7, {"v": jnp.asarray(3.0)})
        ckpt.wait()
        target = d / "step_000000000007"
        assert sorted(os.listdir(target)) == ["MANIFEST.json",
                                              "state.pkl"]
        assert float(np.asarray(ckpt.restore(7)["v"])) == 3.0
        assert not stale.exists()

    def test_kill_between_rotate_and_promote_recovers(self, hvt,
                                                      tmp_path):
        """A save killed after rotating the old step aside (step_N ->
        step_N.old, before the staged promote) must not lose the last
        durable payload: restore falls back to the rotated copy."""
        import os

        import jax.numpy as jnp

        d = tmp_path / "ck"
        ckpt = hvt.Checkpointer(str(d), use_orbax=False)
        ckpt.save(7, {"v": jnp.asarray(1.0)})
        ckpt.wait()
        # simulate the crash window: old rotated aside, promote never
        # happened
        os.replace(str(d / "step_000000000007"),
                   str(d / "step_000000000007.old"))
        assert float(np.asarray(ckpt.restore(7)["v"])) == 1.0
        # the recovery also put the directory back for listing
        assert ckpt.all_steps() == [7]

    def test_restore_missing_step_and_old_raises_clear_error(
            self, hvt, tmp_path):
        """An explicit step with neither its dir nor the .old recovery
        copy present must fail with a diagnostic naming both, not an
        opaque open() traceback from deeper in the loader."""
        import jax.numpy as jnp

        ckpt = hvt.Checkpointer(str(tmp_path / "ck"), use_orbax=False)
        ckpt.save(7, {"v": jnp.asarray(1.0)})
        ckpt.wait()
        with pytest.raises(FileNotFoundError) as ei:
            ckpt.restore(5)
        msg = str(ei.value)
        assert "step 5" in msg and ".old" in msg

    def test_restore_corrupt_explicit_step_raises(self, hvt, tmp_path):
        """A bit-flipped state.pkl behind an intact manifest is
        rejected when that step was requested explicitly."""
        import jax.numpy as jnp

        d = tmp_path / "ck"
        ckpt = hvt.Checkpointer(str(d), use_orbax=False)
        ckpt.save(3, {"v": jnp.asarray(1.0)})
        ckpt.wait()
        p = d / "step_000000000003" / "state.pkl"
        raw = bytearray(p.read_bytes())
        raw[len(raw) // 2] ^= 0x01
        p.write_bytes(bytes(raw))
        with pytest.raises(ValueError, match="manifest verification"):
            ckpt.restore(3)

    def test_restore_latest_falls_back_past_corrupt_step(self, hvt,
                                                         tmp_path):
        """Latest-step restore skips a corrupt newest checkpoint and
        loads the previous retained one."""
        import jax.numpy as jnp

        d = tmp_path / "ck"
        ckpt = hvt.Checkpointer(str(d), use_orbax=False)
        ckpt.save(1, {"v": jnp.asarray(1.0)})
        ckpt.save(2, {"v": jnp.asarray(2.0)})
        ckpt.wait()
        p = d / "step_000000000002" / "state.pkl"
        raw = bytearray(p.read_bytes())
        raw[len(raw) // 2] ^= 0x01
        p.write_bytes(bytes(raw))
        out = ckpt.restore()
        assert float(np.asarray(out["v"])) == 1.0

    def test_async_save_overlaps(self, hvt, tmp_path):
        import jax.numpy as jnp

        ckpt = hvt.Checkpointer(str(tmp_path / "ck"))
        ckpt.save(1, {"big": jnp.ones((256, 256))})
        # a second save waits for the first (one in flight), both land
        ckpt.save(2, {"big": jnp.zeros((256, 256))})
        ckpt.wait()
        assert ckpt.all_steps() == [1, 2]
