"""Eager mini-controller tests.

The reference exercises its controller via the async torch API under
horovodrun (SURVEY.md §4).  Here, multi-rank negotiation runs as N
controller instances over an in-memory KV store (the localhost-as-
cluster pattern at the thread level); the XLA data plane degenerates to
local math in a 1-process world, which is exactly what we want: these
tests pin the *coordination* semantics.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.comm.compression import Compression
from horovod_tpu.comm.reduce_ops import ReduceOp
from horovod_tpu.core.exceptions import HorovodInternalError
from horovod_tpu.eager.controller import EagerController, KVTransport
from horovod_tpu.native import wire


class FakeKV:
    """In-memory stand-in for the JAX coordination-service KV client."""

    def __init__(self):
        self._lock = threading.Condition()
        self._store = {}

    def key_value_set(self, key, value):
        with self._lock:
            self._store[key] = value
            self._lock.notify_all()

    def blocking_key_value_get(self, key, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000.0
        with self._lock:
            while key not in self._store:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"KV key {key} not set")
                self._lock.wait(remaining)
            return self._store[key]

    def key_value_delete(self, key):
        with self._lock:
            self._store.pop(key, None)


def make_world(size, **kw):
    kv = FakeKV()
    ctrls = [
        EagerController(
            r, size,
            transport=KVTransport(r, size, client=kv, timeout_s=20.0),
            cycle_time_ms=0.5,
            **kw,
        )
        for r in range(size)
    ]
    for c in ctrls:
        c.start()
    return ctrls


def stop_world(ctrls):
    # announce shutdown everywhere FIRST so no controller lingers
    # waiting for the others' agreement (coordinated-shutdown parity)
    for c in ctrls:
        c.request_shutdown()
    for c in ctrls:
        c.stop()


# --------------------------------------------------------------------------
# single-process (LocalTransport) behavior through the public API
# --------------------------------------------------------------------------

class TestSingleProcess:
    def test_allreduce_async_roundtrip(self, hvt):
        h = hvt.allreduce_async(jnp.arange(6.0), average=False, name="t0")
        out = hvt.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.arange(6.0))

    def test_poll_completes(self, hvt):
        h = hvt.allreduce_async(jnp.ones((4,)), average=True, name="t1")
        deadline = time.monotonic() + 10
        while not hvt.poll(h):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        out = hvt.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_out_of_order_many(self, hvt):
        handles = {
            name: hvt.allreduce_async(jnp.full((3,), float(i)), name=name)
            for i, name in enumerate(["z", "b", "q", "a"])
        }
        for i, name in enumerate(["z", "b", "q", "a"]):
            out = hvt.synchronize(handles[name])
            np.testing.assert_allclose(np.asarray(out), float(i))

    def test_all_op_kinds(self, hvt):
        ha = hvt.allgather_async(jnp.arange(4.0), name="ag")
        hb = hvt.broadcast_async(jnp.full((2,), 7.0), 0, name="bc")
        hr = hvt.reducescatter_async(jnp.arange(8.0), name="rs")
        np.testing.assert_allclose(np.asarray(hvt.synchronize(ha)),
                                   np.arange(4.0))
        np.testing.assert_allclose(np.asarray(hvt.synchronize(hb)), 7.0)
        hvt.synchronize(hr)

    def test_grouped_allreduce_async(self, hvt):
        tensors = [jnp.full((2,), 1.0), jnp.full((3,), 2.0)]
        handles = hvt.grouped_allreduce_async(
            tensors, names=["ga/x", "ga/y"], average=False
        )
        outs = [hvt.synchronize(h) for h in handles]
        np.testing.assert_allclose(np.asarray(outs[0]), 1.0)
        np.testing.assert_allclose(np.asarray(outs[1]), 2.0)

    def test_duplicate_pending_name_fails(self, hvt):
        # manual mode: no background cycle can drain the first enqueue
        # between the two calls (that made this racy before)
        ctrl = EagerController(0, 1, manual=True)
        try:
            f1 = ctrl.enqueue("allreduce", jnp.ones(2), name="dup")
            f2 = ctrl.enqueue("allreduce", jnp.ones(2), name="dup")
            with pytest.raises(HorovodInternalError, match="duplicate"):
                f2.result(timeout=5)
            ctrl.run_cycle_once()
            f1.result(timeout=5)
        finally:
            ctrl.stop()

    def test_join_single(self, hvt):
        assert hvt.join() == 0

    def test_compression_fused(self, hvt):
        hs = [
            hvt.allreduce_async(
                jnp.full((4,), 3.0), name=f"c/{i}",
                compression=Compression.fp16, average=False,
            )
            for i in range(3)
        ]
        for h in hs:
            out = hvt.synchronize(h)
            assert out.dtype == jnp.float32
            np.testing.assert_allclose(np.asarray(out), 3.0)


# --------------------------------------------------------------------------
# multi-rank negotiation over the KV transport
#
# The N "ranks" are N controller instances in one process; the XLA data
# plane underneath each runs in this process's 1-rank world (so results
# are local values) — these tests pin negotiation, not the math.  The
# `hvt` fixture initializes that 1-rank world for the data plane.
# --------------------------------------------------------------------------

class TestMultiRankNegotiation:
    def test_out_of_order_enqueue_resolves(self, hvt):
        ctrls = make_world(2)
        try:
            # rank 0 enqueues a then b; rank 1 enqueues b then a — the
            # exact reordering scenario the controller exists for.
            fa0 = ctrls[0].enqueue("allreduce", jnp.ones(4), name="a")
            fb0 = ctrls[0].enqueue("allreduce", jnp.ones(4), name="b")
            fb1 = ctrls[1].enqueue("allreduce", jnp.ones(4), name="b")
            fa1 = ctrls[1].enqueue("allreduce", jnp.ones(4), name="a")
            for f in (fa0, fb0, fb1, fa1):
                f.result(timeout=20)
        finally:
            stop_world(ctrls)

    def test_partial_submission_waits(self, hvt):
        ctrls = make_world(2)
        try:
            f0 = ctrls[0].enqueue("allreduce", jnp.ones(2), name="only0")
            time.sleep(0.2)
            assert not f0.done()  # rank 1 never submitted
            f1 = ctrls[1].enqueue("allreduce", jnp.ones(2), name="only0")
            f0.result(timeout=20)
            f1.result(timeout=20)
        finally:
            stop_world(ctrls)

    def test_dynamic_join(self, hvt):
        ctrls = make_world(2)
        try:
            jf0 = ctrls[0].join()
            # join resolves only after EVERY rank joins; rank 1 is late.
            time.sleep(0.1)
            assert not jf0.done()
            jf1 = ctrls[1].join()
            assert jf0.result(timeout=20) == 1
            assert jf1.result(timeout=20) == 1
        finally:
            stop_world(ctrls)

    def test_join_unblocks_remaining_ranks(self, hvt):
        """VERDICT round-1 Missing #4: after rank 1 joins, rank 0's
        subsequent collectives complete (rank 1 implicitly ready with a
        zero contribution) instead of stalling until abort."""
        ctrls = make_world(2)
        try:
            # both ranks run one normal batch
            f0 = ctrls[0].enqueue("allreduce", jnp.ones(4), name="b0")
            f1 = ctrls[1].enqueue("allreduce", jnp.ones(4), name="b0")
            f0.result(timeout=20), f1.result(timeout=20)
            # rank 1 exhausts its data and joins
            jf1 = ctrls[1].join()
            # rank 0 keeps training: 2 more steps, must NOT stall
            for step in range(2):
                f = ctrls[0].enqueue(
                    "allreduce", jnp.ones(4), name=f"late{step}"
                )
                f.result(timeout=20)
            assert not jf1.done()  # join still pending (rank 0 not joined)
            jf0 = ctrls[0].join()
            # rank 0 joined last -> join() returns 0 on every rank
            assert jf0.result(timeout=20) == 0
            assert jf1.result(timeout=20) == 0
        finally:
            stop_world(ctrls)

    def test_join_unblocks_allgather_and_broadcast(self, hvt):
        ctrls = make_world(2)
        try:
            ctrls[1].join()
            fg = ctrls[0].enqueue("allgather", jnp.ones((2, 3)), name="g")
            fb = ctrls[0].enqueue("broadcast", jnp.ones(3), name="bc",
                                  root_rank=0)
            fg.result(timeout=20)
            fb.result(timeout=20)
            ctrls[0].join().result(timeout=20)
        finally:
            stop_world(ctrls)

    def test_shutdown_error_reaches_only_enqueuers(self, hvt):
        """A 'rank N has shut down' error response is broadcast to all
        ranks; members that never enqueued the tensor must IGNORE it
        (not kill their cycle thread), and the enqueuer's future gets
        the error."""
        ctrls = make_world(3)
        try:
            f0 = ctrls[0].enqueue("allreduce", jnp.ones(2), name="dead")
            ctrls[2].request_shutdown()
            with pytest.raises(HorovodInternalError,
                               match="rank 2 has shut down"):
                f0.result(timeout=20)
            # ranks 1 and 2 saw the same error response without having
            # the payload; their cycle threads must still be healthy
            time.sleep(0.1)
            assert ctrls[1]._thread_error is None
            assert ctrls[2]._thread_error is None
        finally:
            stop_world(ctrls)

    def test_same_name_in_disjoint_process_sets(self):
        """The coordination table is scoped per process set: the same
        tensor name pending in two disjoint sets must not collide
        (parity: each ProcessSet owns its own controller/MessageTable).
        Driven at the protocol level: 4 ranks, sets {0,2} and {1,3},
        all four report tensor 'x' — the coordinator must emit TWO
        responses, one per set, each only when ITS members reported."""
        from horovod_tpu.native.fallback import PyController

        coord = PyController(0, 4, fusion_threshold=1 << 20)
        coord.register_process_set(1, [0, 2])
        coord.register_process_set(2, [1, 3])
        workers = []
        for r in range(4):
            c = PyController(r, 4, fusion_threshold=1 << 20)
            c.register_process_set(1, [0, 2])
            c.register_process_set(2, [1, 3])
            workers.append(c)
        # ranks 0 and 1 report 'x' for their respective sets
        workers[0].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,),
                           process_set_id=1)
        workers[1].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (5,),
                           process_set_id=2)
        coord.ingest(workers[0].drain_requests())
        coord.ingest(workers[1].drain_requests())
        rl = wire.parse_response_list(coord.compute_responses())
        assert rl.responses == []  # neither set complete yet
        # remaining members report
        workers[2].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,),
                           process_set_id=1)
        workers[3].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (5,),
                           process_set_id=2)
        coord.ingest(workers[2].drain_requests())
        coord.ingest(workers[3].drain_requests())
        rl = wire.parse_response_list(coord.compute_responses())
        assert len(rl.responses) == 2
        by_ps = {rs.process_set_id: rs for rs in rl.responses}
        assert by_ps[1].tensor_names == ["x"]
        assert by_ps[1].tensor_shapes == [(2,)]
        assert by_ps[2].tensor_names == ["x"]
        assert by_ps[2].tensor_shapes == [(5,)]

    def test_steady_state_bypass_observable_and_bit_identical(self, hvt):
        """Acceptance: a same-shape allreduce loop reports
        hvtpu_controller_bypass_cycles_total > 0, and the results of
        bypass cycles are bit-identical to full cycles (resync_every=0
        disables the fast path entirely)."""
        from horovod_tpu.obs import metrics as obs_metrics

        bypass_ctr = obs_metrics.counter(
            "hvtpu_controller_bypass_cycles_total")

        def run_loop(disable_bypass):
            ctrls = make_world(2)
            if disable_bypass:
                for c in ctrls:
                    c._ctrl.set_resync_every(0)
            outs = []
            try:
                for step in range(5):
                    futs = []
                    for c in ctrls:
                        for i in range(3):
                            futs.append(c.enqueue(
                                "allreduce",
                                jnp.full((8,), float(step * 3 + i)),
                                name=f"bp/{i}", op=ReduceOp.SUM,
                            ))
                    outs.extend(np.asarray(f.result(timeout=20))
                                for f in futs)
            finally:
                stop_world(ctrls)
            return np.stack(outs)

        base = bypass_ctr.value()
        with_bypass = run_loop(disable_bypass=False)
        assert bypass_ctr.value() > base
        mid = bypass_ctr.value()
        without = run_loop(disable_bypass=True)
        assert bypass_ctr.value() == mid  # fast path really was off
        np.testing.assert_array_equal(with_bypass, without)

    def test_predicted_fast_path_opt_in(self, hvt, monkeypatch):
        """HVTPU_EAGER_PREDICT=1 (experimental): a steady same-shape
        loop eventually executes predicted schedules without waiting
        for the coordinator round trip, with correct results."""
        import numpy as np

        from horovod_tpu.obs import metrics as obs_metrics

        monkeypatch.setenv("HVTPU_EAGER_PREDICT", "1")
        pred = obs_metrics.counter(
            "hvtpu_controller_predicted_cycles_total")
        base = pred.value()
        ctrls = make_world(2)
        try:
            for step in range(30):
                futs = [c.enqueue("allreduce",
                                  jnp.full((4,), float(step)),
                                  name=f"pr/{i}")
                        for c in ctrls for i in range(2)]
                for f in futs:
                    np.testing.assert_allclose(
                        np.asarray(f.result(timeout=20)), float(step))
                if pred.value() > base:
                    break
        finally:
            stop_world(ctrls)
        assert pred.value() > base

    @pytest.mark.chaos
    def test_kv_faults_during_bypass_cycles_recover(self, hvt):
        """Chaos: seeded error-injected KV writes during steady-state
        bypass cycles are retried by the transport (UNAVAILABLE is
        transient) and every future still resolves."""
        from horovod_tpu.core import faults as core_faults
        from horovod_tpu.obs import metrics as obs_metrics

        bypass_ctr = obs_metrics.counter(
            "hvtpu_controller_bypass_cycles_total")
        base = bypass_ctr.value()
        core_faults.install("kv.put:error@prob=0.2,times=12", rank=0,
                            seed=11)
        try:
            ctrls = make_world(2)
            try:
                for step in range(8):
                    futs = [c.enqueue("allreduce", jnp.ones(4),
                                      name=f"ch/{step % 2}")
                            for c in ctrls]
                    for f in futs:
                        f.result(timeout=30)
            finally:
                stop_world(ctrls)
        finally:
            core_faults.uninstall()
        assert bypass_ctr.value() > base  # faults hit the fast path

    def test_steady_state_cache_and_fusion(self, hvt):
        ctrls = make_world(2, fusion_threshold=1 << 20)
        try:
            for step in range(3):
                futs = []
                for c in ctrls:
                    for i in range(4):
                        futs.append(c.enqueue(
                            "allreduce", jnp.full((8,), float(step)),
                            name=f"g/{i}", op=ReduceOp.SUM,
                        ))
                for f in futs:
                    f.result(timeout=20)
            assert ctrls[0]._ctrl.cache_size == 4
        finally:
            stop_world(ctrls)

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_stall_abort_fails_futures(self, hvt):
        ctrls = make_world(2, stall_warn_s=0.0, stall_abort_s=0.3)
        try:
            f0 = ctrls[0].enqueue("allreduce", jnp.ones(2), name="never")
            with pytest.raises(HorovodInternalError):
                f0.result(timeout=30)
        finally:
            stop_world(ctrls)

    def test_shutdown_fails_pending(self, hvt):
        ctrls = make_world(2)
        f0 = ctrls[0].enqueue("allreduce", jnp.ones(2), name="pend")
        stop_world(ctrls)
        with pytest.raises(HorovodInternalError):
            f0.result(timeout=5)


# --------------------------------------------------------------------------
# default-on schedule prediction (atomic burst units make it sound)
# --------------------------------------------------------------------------

class TestPredictedSchedules:
    def _run_steady(self, ctrls, steps, start=0, names=2, width=2):
        for step in range(start, start + steps):
            futs = [c.enqueue("allreduce", jnp.full((4,), float(step)),
                              name=f"ps/{i}")
                    for c in ctrls for i in range(names)]
            for f in futs:
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=20)), float(step))

    def test_predicted_default_on_confirms_and_drains(self, hvt):
        """HVTPU_EAGER_PREDICT defaults to auto: a steady same-shape
        loop predicts schedules, the post-hoc confirm hashes drain the
        outstanding-prediction FIFO, and nothing mispredicts."""
        from horovod_tpu.obs import metrics as obs_metrics

        pred = obs_metrics.counter(
            "hvtpu_controller_predicted_cycles_total")
        misp = obs_metrics.counter("hvtpu_controller_mispredicts_total")
        base_p, base_m = pred.value(), misp.value()
        ctrls = make_world(2)
        try:
            self._run_steady(ctrls, steps=30)
            assert pred.value() > base_p
            assert misp.value() == base_m
            # quiesce waits for outstanding confirmations, then idles
            for c in ctrls:
                assert c.quiesce(timeout=10) is True
                assert not c._predicted
        finally:
            stop_world(ctrls)

    def test_predict_confirm_instants_traced(self, hvt, tmp_path):
        """PR 12: every drained prediction leaves a ``predict_confirm``
        instant (how=hash for suppressed bursts, how=byte-verify for
        streamed ones) naming its tensors, so hvtputrace can tell
        confirmed PREDICT spans from aborted ones; a clean steady run
        traces zero mispredict instants."""
        import json as _json

        from horovod_tpu.obs import tracing

        ctrls = make_world(2)
        tracing.install(str(tmp_path), rank=0, size=1)
        try:
            self._run_steady(ctrls, steps=30)
            for c in ctrls:
                assert c.quiesce(timeout=10) is True
        finally:
            stop_world(ctrls)
            tracing.uninstall()
        with open(tmp_path / "rank0.trace.json") as f:
            evs = _json.load(f)
        confirms = [e for e in evs
                    if e.get("name") == "predict_confirm"]
        assert confirms, "no predict_confirm instants traced"
        for e in confirms:
            assert e["args"]["how"] in ("hash", "byte-verify")
            assert e["args"]["names"]
        assert not any(e.get("name") == "mispredict" for e in evs)

    def test_gate_and_predict_state_reset_across_cache_resync(self, hvt):
        """Satellite: a coordinator-forced resync must reset the burst
        gate's _expected_burst ITSELF (and the predict eligibility
        latch), not just the stability counter — a stale steady size
        from before a resize would gate the wrong burst shape."""
        ctrl = EagerController(0, 1, manual=True)
        try:
            with ctrl._lock:
                ctrl._expected_burst = 4
                ctrl._burst_stable = 5
                ctrl._verified_bits.add((1, 2, 3))
                ctrl._observe.append(((1, 2), [], []))
                ctrl._predicted.append(
                    {"hash": 0x1234, "responses": [], "names": ["rx"]})
            ctrl._dispatch_execution(
                wire.ResponseList(cache_resync_needed=True), [])
            assert ctrl._expected_burst == 0
            assert ctrl._burst_stable == 0
            assert not ctrl._verified_bits
            assert not ctrl._observe
            assert not ctrl._predicted
            # abandoned predicted names are tolerated, not fatal, if
            # their real responses arrive later
            assert "rx" in ctrl._mispredict_names
        finally:
            ctrl.stop()

    def test_gate_and_predict_state_reset_on_membership_change(self, hvt):
        """Same latch reset on an elastic membership change
        (join_last_rank >= 0) and on a mismatch error response."""
        for rl in (
            wire.ResponseList(join_last_rank=1),
            wire.ResponseList(responses=[wire.Response(
                tensor_names=["e"], tensor_shapes=[(2,)],
                error="cross-rank mismatch")]),
        ):
            ctrl = EagerController(0, 1, manual=True)
            try:
                with ctrl._lock:
                    ctrl._expected_burst = 3
                    ctrl._burst_stable = 7
                ctrl._dispatch_execution(rl, [])
                assert ctrl._expected_burst == 0
                assert ctrl._burst_stable == 0
            finally:
                ctrl.stop()

    def test_mispredict_forces_resync_and_converges(self, hvt):
        """Satellite: the mispredict recovery path — counter bump,
        forced full negotiation + cache-resync re-anchor — converges:
        the world keeps producing correct results afterwards."""
        from horovod_tpu.obs import metrics as obs_metrics

        pred = obs_metrics.counter(
            "hvtpu_controller_predicted_cycles_total")
        misp = obs_metrics.counter("hvtpu_controller_mispredicts_total")
        ctrls = make_world(2)
        try:
            base_p, base_m = pred.value(), misp.value()
            self._run_steady(ctrls, steps=30)
            assert pred.value() > base_p  # steady state reached
            with ctrls[0]._lock:
                ctrls[0]._on_mispredict("test-injected disagreement")
            assert misp.value() == base_m + 1
            # forced resync converges: further steps correct, threads
            # healthy, and the gate latch was dropped
            self._run_steady(ctrls, steps=10, start=30)
            for c in ctrls:
                assert c._thread_error is None
                assert c.quiesce(timeout=10) is True
        finally:
            stop_world(ctrls)

    def test_preempt_pending_blocks_new_predictions(self, hvt, monkeypatch):
        """Satellite: once a drain is pending, no NEW speculation may
        start (quiesce handles predictions already in flight)."""
        from horovod_tpu.core import preempt
        from horovod_tpu.obs import metrics as obs_metrics

        pred = obs_metrics.counter(
            "hvtpu_controller_predicted_cycles_total")
        monkeypatch.setattr(preempt, "PENDING", True)
        base = pred.value()
        ctrls = make_world(2)
        try:
            self._run_steady(ctrls, steps=20)
            assert pred.value() == base
        finally:
            stop_world(ctrls)

    def test_quiesce_rolls_back_unconfirmed_predictions(
            self, hvt, monkeypatch):
        """Satellite: a predicted cycle whose confirmation never
        arrives must not block the emergency commit forever — at the
        quiesce deadline the predictor rolls back to full negotiation
        and re-anchors exactly as if the coordinator had requested
        cache_resync_needed."""
        monkeypatch.setenv("HVTPU_FORCE_PY_CONTROLLER", "1")
        ctrl = EagerController(0, 1, manual=True)
        try:
            with ctrl._lock:
                ctrl._predicted.append(
                    {"hash": 0xDEAD, "responses": [], "names": ["q1"]})
            t0 = time.monotonic()
            assert ctrl.quiesce(timeout=0.4) is True
            # it WAITED for the confirmation before giving up on it
            assert time.monotonic() - t0 >= 0.35
            assert not ctrl._predicted
            assert "q1" in ctrl._mispredict_names
            # rollback re-anchors: next drain is a full resync frame
            assert ctrl._ctrl._resync_flush
        finally:
            ctrl.stop()

    def test_burst_hint_arms_gate_and_is_consumed_by_drain(self, hvt):
        """The frontend burst hint (torch optimizer's per-step grad
        count) arms the gate before stability forms, and a drain that
        covers the hinted count consumes it — a partial drain keeps
        the hint armed for the rest of the burst."""
        ctrl = EagerController(0, 1, manual=True)
        try:
            ctrl.hint_burst(4)
            assert ctrl._burst_hint == 4
            blob = wire.serialize_request_list(wire.RequestList(rank=0))
            ctrl._note_drained(2, blob)  # burst split: hint survives
            assert ctrl._burst_hint == 4
            ctrl._note_drained(4, blob)  # full burst: hint consumed
            assert ctrl._burst_hint == 0
            ctrl.hint_burst(-3)  # defensive clamp, never negative
            assert ctrl._burst_hint == 0
        finally:
            ctrl.stop()

    def test_burst_cap_drains_one_unit(self, hvt, monkeypatch):
        """With a verified steady burst, each drain is capped at the
        burst size so one wire unit == one application burst; the
        opt-out knob restores unbounded drains."""
        monkeypatch.setenv("HVTPU_EAGER_BURST_CAP", "0")
        ctrl = EagerController(0, 1, manual=True)
        try:
            assert ctrl._burst_cap_on is False
        finally:
            ctrl.stop()
        monkeypatch.delenv("HVTPU_EAGER_BURST_CAP")
        ctrl = EagerController(0, 1, manual=True)
        try:
            assert ctrl._burst_cap_on is True
        finally:
            ctrl.stop()


# --------------------------------------------------------------------------
# zero-copy fusion buffers: the fallback lattice
# ({predicted, mispredicted} x {lockstep, streamed}), pool hygiene on
# quiesce, and the non-steady enqueue overhead guard.  Packing-level
# contracts live in tests/test_fusion_buffers.py.
# --------------------------------------------------------------------------

def _fusion_counters():
    from horovod_tpu.obs import metrics as obs_metrics

    return (obs_metrics.counter("hvtpu_fusion_zero_copy_ops_total"),
            obs_metrics.counter("hvtpu_fusion_staged_copies_total"))


class TestZeroCopyFusion:
    def _steady_manual(self, ctrl, steps, start=0, names=2):
        """Lockstep analog of TestPredictedSchedules._run_steady: the
        same 2-op burst each cycle, driven by run_cycle_once."""
        for step in range(start, start + steps):
            futs = [ctrl.enqueue("allreduce",
                                 jnp.full((4,), float(step)),
                                 name=f"zc/{i}")
                    for i in range(names)]
            ctrl.run_cycle_once()
            for f in futs:
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=10)), float(step))

    def test_predicted_lockstep_packs_at_enqueue(self, hvt):
        """Cell 1: steady lockstep bursts learn a pack plan from the
        staged path, then every later burst rides the zero-copy path
        (enqueue-time pack, typed-view wire tensor, lazy unpack)."""
        zc, st = _fusion_counters()
        ctrl = EagerController(0, 1, manual=True)
        try:
            b_zc, b_st = zc.value(), st.value()
            self._steady_manual(ctrl, steps=4)
            # warmup bursts staged (stability bar + plan learning)...
            assert st.value() - b_st >= 2
            assert ctrl._pack_plan is not None
            assert set(ctrl._pack_plan) == {"zc/0", "zc/1"}
            mid = zc.value()
            self._steady_manual(ctrl, steps=3, start=4)
            # ...then EVERY op of every burst is zero-copy
            assert zc.value() - mid == 3 * 2
            # drained packs went back to the pool, none left open
            assert not ctrl._open_packs
            assert ctrl._fusion_pool.stats()["pooled"] >= 1
        finally:
            ctrl.stop()

    def test_mispredicted_lockstep_falls_back_staged(self, hvt):
        """Cell 2: a mispredict between enqueue (payloads already
        packed) and drain releases the open packs, drops the plan, and
        the drain takes the staged path — correct results, staged
        counter increment, resync forced."""
        zc, st = _fusion_counters()
        ctrl = EagerController(0, 1, manual=True)
        try:
            self._steady_manual(ctrl, steps=4)
            assert ctrl._pack_plan is not None
            futs = [ctrl.enqueue("allreduce", jnp.full((4,), 9.0),
                                 name=f"zc/{i}") for i in range(2)]
            assert ctrl._open_packs  # enqueue-time pack happened
            b_zc, b_st = zc.value(), st.value()
            with ctrl._lock:
                ctrl._on_mispredict("test-injected disagreement")
            # rollback released the packed-but-undrained buffers and
            # forgot the plan: fail back to correct, never to fast
            assert not ctrl._open_packs
            assert ctrl._pack_plan is None
            ctrl.run_cycle_once()
            for f in futs:
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=10)), 9.0)
            assert st.value() - b_st == 2
            assert zc.value() == b_zc
        finally:
            ctrl.stop()

    def test_stale_grouping_releases_pack_and_stages(self, hvt):
        """A burst whose agreed grouping no longer matches the learned
        plan (extra op joins the fusion group) must not ride a
        partial pack: staged path, correct results."""
        zc, st = _fusion_counters()
        ctrl = EagerController(0, 1, manual=True)
        try:
            self._steady_manual(ctrl, steps=4)
            assert ctrl._pack_plan is not None
            b_zc, b_st = zc.value(), st.value()
            futs = [ctrl.enqueue("allreduce", jnp.full((4,), 5.0),
                                 name=f"zc/{i}") for i in range(2)]
            futs.append(ctrl.enqueue("allreduce", jnp.full((4,), 5.0),
                                     name="zc/extra"))
            ctrl.run_cycle_once()
            for f in futs:
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=10)), 5.0)
            assert st.value() - b_st == 3  # whole group staged
            assert zc.value() == b_zc
            # the stranded 2-name pack is reclaimed by quiesce
            assert ctrl.quiesce(timeout=5) is True
            assert not ctrl._open_packs
        finally:
            ctrl.stop()

    def test_predicted_streamed_goes_zero_copy(self, hvt):
        """Cell 3: the streamed plane's steady predicted schedule
        drives the same enqueue-time pack — zero-copy ops accumulate,
        zero mispredicts, results exact."""
        from horovod_tpu.obs import metrics as obs_metrics

        zc, st = _fusion_counters()
        misp = obs_metrics.counter("hvtpu_controller_mispredicts_total")
        ctrls = make_world(2)
        try:
            b_zc, b_m = zc.value(), misp.value()
            TestPredictedSchedules._run_steady(self, ctrls, steps=30)
            assert zc.value() - b_zc > 0
            assert misp.value() == b_m
            for c in ctrls:
                assert c._pack_plan is not None
                assert c.quiesce(timeout=10) is True
                assert not c._open_packs
        finally:
            stop_world(ctrls)

    def test_mispredicted_streamed_re_anchors_and_recovers(self, hvt):
        """Cell 4: a streamed mispredict re-anchors through resync —
        the plan drops, later bursts stage (counter increment), results
        stay exact, and a re-proven schedule resumes zero-copy."""
        zc, st = _fusion_counters()
        ctrls = make_world(2)
        try:
            TestPredictedSchedules._run_steady(self, ctrls, steps=30)
            assert zc.value() > 0
            b_st = st.value()
            with ctrls[0]._lock:
                ctrls[0]._on_mispredict("test-injected disagreement")
            assert ctrls[0]._pack_plan is None
            TestPredictedSchedules._run_steady(self, ctrls, steps=10,
                                               start=30)
            assert st.value() - b_st > 0  # post-mispredict bursts staged
            mid_zc = zc.value()
            TestPredictedSchedules._run_steady(self, ctrls, steps=25,
                                               start=40)
            assert zc.value() > mid_zc  # schedule re-proven, fast again
            for c in ctrls:
                assert c._thread_error is None
                assert c.quiesce(timeout=10) is True
        finally:
            stop_world(ctrls)

    def test_quiesce_returns_pooled_buffers_before_commit(self, hvt):
        """Preempt-drain hygiene: quiesce() returns open exchange
        buffers to the pool before reporting idle, so the emergency
        commit never snapshots around a dangling pack."""
        ctrl = EagerController(0, 1, manual=True)
        try:
            specs = [((4,), np.dtype(np.float32), 16)]
            with ctrl._lock:
                ctrl._open_packs[(0, ("qa", "qb"))] = (
                    ctrl._fusion_pool.acquire(0, specs))
            assert ctrl._fusion_pool.stats()["pooled"] == 0
            assert ctrl.quiesce(timeout=5) is True
            assert not ctrl._open_packs
            assert ctrl._fusion_pool.stats()["pooled"] == 1
        finally:
            ctrl.stop()

    def test_nonsteady_enqueue_prepack_is_under_5us(self, hvt):
        """Acceptance: with no pack plan (the non-steady state every
        rank starts in), the enqueue-path hook is one None check —
        same budget discipline as the flight recorder's disabled-path
        guard."""
        import timeit

        from horovod_tpu.comm.compression import NoneCompressor
        from horovod_tpu.eager.controller import _Payload

        ctrl = EagerController(0, 1, manual=True)
        try:
            assert ctrl._pack_plan is None
            p = _Payload(
                seq=1, name="t/0", future=None, tensor=jnp.ones(4),
                rop=ReduceOp.SUM, prescale=1.0, postscale=1.0,
                compressor=NoneCompressor, splits=None,
                kind="allreduce", process_set=None, psid=0,
                root_rank=-1, t_enqueue=0.0)
            n = 100_000
            t = timeit.timeit(lambda: ctrl._maybe_prepack(p), number=n)
            assert t / n < 5e-6, f"prepack hook: {t / n * 1e9:.0f} ns/op"
        finally:
            ctrl.stop()
