"""TF-tensor collectives over the TPU engine.

Parity surface: ``horovod/tensorflow/mpi_ops.py`` + the C++ custom-op
binding ``horovod/tensorflow/mpi_ops.cc`` (``HorovodAllreduceOp`` …).

Adapter design: the reference registers TF custom kernels; here the
boundary is tf ↔ jax via DLPack — zero host copy for eager CPU tensors
in both directions (parity: the TFTensor adapter in mpi_ops.cc wrapping
the TF buffer directly; same contract as the torch adapter), with a
numpy fallback for float64 (jax x64 semantics) and exotic layouts.
Inside a ``tf.function`` graph the ops route through ``tf.py_function``
(the engine executes eagerly mid-graph), keeping user code with
``@tf.function`` training steps working unchanged — the role
``xla_mpi_ops.cc``'s CustomCall plays in the reference.
``tf.IndexedSlices`` gradients take the values+indices allgather path
like the reference's sparse handling.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
import tensorflow as tf

import horovod_tpu as _hvt

from .compression import BF16Compressor, Compression, FP16Compressor

Sum = _hvt.Sum
Average = _hvt.Average
Adasum = _hvt.Adasum
Min = _hvt.Min
Max = _hvt.Max
Product = _hvt.Product


def _engine_compression(compression):
    from ..comm.compression import Compression as EngineCompression

    if compression is FP16Compressor or compression is Compression.fp16:
        return EngineCompression.fp16
    if compression is BF16Compressor or compression is Compression.bf16:
        return EngineCompression.bf16
    return EngineCompression.none


from ..core.process_set import (
    participant_count as _participant_count,
    participant_rank as _participant_rank,
)


def predivide_scaling(op, gradient_predivide_factor: float, process_set):
    """Reference semantics for gradient_predivide_factor: Average
    becomes Sum with the averaging split into prescale=1/factor and
    postscale=factor/N over the participating ranks (parity:
    horovod/torch/optimizer.py + horovod/tensorflow/__init__.py).
    Returns (op, prescale, postscale).  Shared by the tape and the
    keras optimizer so the math cannot drift apart.
    """
    if gradient_predivide_factor == 1.0 or op != Average:
        return op, 1.0, 1.0
    n = _participant_count(process_set)
    return (Sum, 1.0 / gradient_predivide_factor,
            gradient_predivide_factor / n)


def _np(t) -> np.ndarray:
    if isinstance(t, tf.Tensor) or isinstance(t, tf.Variable):
        return t.numpy()
    return np.asarray(t)


def _to_engine(t):
    """tf → jax with zero host copy via DLPack for eager CPU tensors
    (fallback: numpy).  float64 stays on the numpy path so jax's x64
    truncation semantics match the torch adapter."""
    if isinstance(t, tf.Variable):
        # snapshot: variable.assign would mutate the underlying buffer
        # in place while JAX treats the DLPack-imported array as
        # immutable — zero-copy stays reserved for plain eager tensors
        t = tf.identity(t.value())
    if isinstance(t, tf.Tensor):
        if t.dtype == tf.float64:
            return t.numpy()
        try:
            return jax.dlpack.from_dlpack(
                tf.experimental.dlpack.to_dlpack(t)
            )
        except Exception:
            return _np(t)
    return np.asarray(t)


def _from_engine(arr, dtype=None):
    """jax → tf sharing the engine's output buffer via DLPack (numpy
    copy fallback); restores the caller's dtype like the reference's
    decompress-to-input-dtype convention."""
    try:
        out = tf.experimental.dlpack.from_dlpack(arr.__dlpack__())
    except Exception:
        out = tf.convert_to_tensor(np.asarray(arr))
    if dtype is not None and out.dtype != dtype:
        out = tf.cast(out, dtype)
    return out


def _graph_op(fn, inputs, out_dtype, out_shape=None):
    """Run ``fn`` (an engine call accepting jax/numpy arrays) inside a
    TF graph via tf.py_function; in eager mode call it directly on the
    DLPack-shared buffers."""
    if tf.executing_eagerly():
        return _from_engine(fn(*[_to_engine(i) for i in inputs]),
                            dtype=out_dtype)

    def _np_out(o):
        a = np.asarray(o)
        # py_function's Tout contract is strict: restore the declared
        # dtype when the engine computed narrower (float64 runs at f32
        # wire precision unless jax x64 is enabled)
        want = getattr(out_dtype, "as_numpy_dtype", None)
        if want is not None and a.dtype != np.dtype(want):
            a = a.astype(want)
        return tf.convert_to_tensor(a)

    out = tf.py_function(
        lambda *ts: _np_out(fn(*[t.numpy() for t in ts])),
        inputs, Tout=out_dtype,
    )
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

# AutoGraph must NOT convert these ops when a user's @tf.function body
# calls them: conversion rewrites internal helper calls (observed:
# tf___to_engine substituted for allreduce under cache-order-dependent
# tracing) and the bodies are host-side engine dispatches anyway.
_no_autograph = tf.autograph.experimental.do_not_convert


@_no_autograph
def allreduce(tensor, average=None, op=None, name=None,
              compression=Compression.none,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None):
    """Averaged (by default) allreduce (parity: hvd.allreduce for TF).

    ``tf.IndexedSlices`` inputs return IndexedSlices assembled from an
    allgather of values and indices (the reference's sparse path).
    """
    if isinstance(tensor, tf.IndexedSlices):
        # parity: _allreduce of IndexedSlices = allgather values+indices
        # (sum = concatenated contributions, scatter-added at apply;
        # average divides values by the PARTICIPATING rank count).
        # Pre/postscale distribute over the sum, so they apply directly
        # to this rank's values.
        values = allgather(tensor.values, process_set=process_set)
        indices = allgather(tensor.indices, process_set=process_set)
        from ..comm.reduce_ops import ReduceOp, normalize_op

        rop = normalize_op(op, average)
        scale = prescale_factor * postscale_factor
        if rop == ReduceOp.AVERAGE:
            scale /= _participant_count(process_set)
        elif rop != ReduceOp.SUM:
            raise NotImplementedError(
                f"IndexedSlices allreduce supports Sum/Average, got {rop}"
            )
        if scale != 1.0:
            values = values * tf.cast(scale, values.dtype)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    def impl(x):
        return _hvt.allreduce(
            x, op=op, average=average, name=name,
            compression=_engine_compression(compression),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set,
        )

    # Gradient registration (parity: RegisterGradient('HorovodAllreduce')
    # in horovod/tensorflow/mpi_ops.py): the gradient of an allreduce is
    # an allreduce of the gradient with the SAME attributes, so
    # tape.gradient through a bare collective is correct without
    # DistributedGradientTape.
    @tf.custom_gradient
    def _op(x):
        y = _graph_op(impl, [x], x.dtype, x.shape)

        def grad(dy):
            from ..comm.reduce_ops import ReduceOp, normalize_op

            rop = normalize_op(op, average)
            if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE,
                           ReduceOp.ADASUM):
                raise NotImplementedError(
                    f"gradient of a {rop.name} allreduce is not "
                    "defined (reference registers gradients for "
                    "sum/average/adasum)")
            return allreduce(
                dy, average=average, op=op,
                compression=compression,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                process_set=process_set)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


@_no_autograph
def grouped_allreduce(tensors: List, average=None, op=None, name=None,
                      compression=Compression.none, process_set=None):
    if tf.executing_eagerly():
        def impl(*xs):
            outs = _hvt.grouped_allreduce(
                [_to_engine(x) for x in xs], op=op, average=average,
                compression=_engine_compression(compression),
                process_set=process_set,
            )
            return tuple(_from_engine(o, dtype=x.dtype)
                         for x, o in zip(xs, outs))

        # Parity: RegisterGradient('HorovodGroupedAllreduce') — the
        # group's gradient is a grouped allreduce of the gradients
        # with the same attributes.
        @tf.custom_gradient
        def _op(*xs):
            ys = impl(*xs)

            def grad(*dys):
                from ..comm.reduce_ops import ReduceOp, normalize_op

                rop = normalize_op(op, average)
                if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE,
                               ReduceOp.ADASUM):
                    raise NotImplementedError(
                        f"gradient of a {rop.name} grouped_allreduce "
                        "is not defined")
                return tuple(grouped_allreduce(
                    list(dys), average=average, op=op,
                    compression=compression, process_set=process_set))

            return ys, grad

        return list(_op(*[tf.convert_to_tensor(t) for t in tensors]))
    return [
        allreduce(t, average=average, op=op, compression=compression,
                  process_set=process_set)
        for t in tensors
    ]


@_no_autograph
def allgather(tensor, name=None, process_set=None):
    """Concatenate along dim 0 across ranks (ragged dim 0 supported)."""

    def impl(x):
        return _hvt.allgather(x, process_set=process_set, name=name)

    shape = tf.TensorShape([None]).concatenate(tensor.shape[1:]) \
        if tensor.shape.rank is not None and tensor.shape.rank > 0 else None

    # Parity: RegisterGradient('HorovodAllgather') — sum the upstream
    # gradient across ranks, then slice out the rows this rank
    # contributed (offsets from the negotiated per-rank dim-0 sizes).
    @tf.custom_gradient
    def _op(x):
        y = _graph_op(impl, [x], x.dtype, shape)

        def grad(dy):
            summed = allreduce(dy, op=Sum, process_set=process_set)
            my_rows = tf.shape(x)[0]
            sizes = allgather(tf.reshape(my_rows, [1]),
                              process_set=process_set)
            r = _participant_rank(process_set)
            offset = tf.reduce_sum(sizes[:r])
            return summed[offset:offset + my_rows]

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


@_no_autograph
def grouped_allgather(tensors: List, name=None, process_set=None):
    """Allgather a list of tensors as one negotiated group (parity:
    hvd.grouped_allgather for TF; ``name`` accepted for signature
    compatibility — members are auto-named like the torch frontend)."""
    if tf.executing_eagerly():
        def impl(*xs):
            outs = _hvt.grouped_allgather(
                [_to_engine(x) for x in xs], process_set=process_set,
            )
            return tuple(_from_engine(o, dtype=x.dtype)
                         for x, o in zip(xs, outs))

        # Parity: RegisterGradient('HorovodGroupedAllgather') — one
        # grouped allreduce-sum of the upstream gradients, then each
        # member slices out the rows this rank contributed.  All
        # members' row counts ride ONE size-allgather ([1, N] per
        # rank), not one collective per member.
        @tf.custom_gradient
        def _op(*xs):
            ys = impl(*xs)

            def grad(*dys):
                summed = grouped_allreduce(
                    list(dys), op=Sum, process_set=process_set)
                r = _participant_rank(process_set)
                rows = tf.stack([tf.shape(x)[0] for x in xs])
                sizes = allgather(tf.reshape(rows, [1, -1]),
                                  process_set=process_set)  # [p, N]
                offsets = tf.reduce_sum(sizes[:r, :], axis=0)
                return tuple(
                    s[offsets[i]:offsets[i] + tf.shape(x)[0]]
                    for i, (x, s) in enumerate(zip(xs, summed)))

            return ys, grad

        return list(_op(*[tf.convert_to_tensor(t) for t in tensors]))
    return [allgather(t, process_set=process_set) for t in tensors]


@_no_autograph
def grouped_reducescatter(tensors: List, op=None, name=None,
                          process_set=None):
    """Reducescatter a list of tensors as one negotiated group (parity:
    hvd.grouped_reducescatter for TF; ``name`` accepted for signature
    compatibility)."""
    if tf.executing_eagerly():
        def impl(*xs):
            outs = _hvt.grouped_reducescatter(
                [_to_engine(x) for x in xs], op=op,
                process_set=process_set,
            )
            return tuple(_from_engine(o, dtype=x.dtype)
                         for x, o in zip(xs, outs))

        # Parity: RegisterGradient('HorovodGroupedReducescatter') —
        # allgather each member's shard gradient; Average forwards
        # additionally average the backward.
        @tf.custom_gradient
        def _op(*xs):
            ys = impl(*xs)

            def grad(*dys):
                from ..comm.reduce_ops import ReduceOp, normalize_op

                rop = normalize_op(op, None)
                if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
                    raise NotImplementedError(
                        f"gradient of a {rop.name} grouped_"
                        "reducescatter is not defined")
                gs = grouped_allgather(list(dys),
                                       process_set=process_set)
                if rop == ReduceOp.AVERAGE:
                    n = _participant_count(process_set)
                    gs = [g / tf.cast(n, g.dtype) for g in gs]
                return tuple(gs)

            return ys, grad

        return list(_op(*[tf.convert_to_tensor(t) for t in tensors]))
    return [reducescatter(t, op=op, process_set=process_set)
            for t in tensors]


@_no_autograph
def broadcast(tensor, root_rank: int = 0, name=None, process_set=None):
    def impl(x):
        return _hvt.broadcast(
            x, root_rank=root_rank, process_set=process_set, name=name
        )

    # Parity: RegisterGradient('HorovodBroadcast') — gradients reduce
    # to the root: every rank allreduce-sums, the root keeps the sum,
    # non-roots get zeros (their input never reached the output).
    @tf.custom_gradient
    def _op(x):
        y = _graph_op(impl, [x], x.dtype, x.shape)

        def grad(dy):
            summed = allreduce(dy, op=Sum, process_set=process_set)
            if _hvt.rank() == root_rank:
                return summed
            return tf.zeros_like(summed)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


@_no_autograph
def alltoall(tensor, splits=None, name=None, process_set=None):
    """Parity: hvd.alltoall — returns (output, received_splits) when
    splits is given, else just the output."""
    if splits is None:
        # Route through the explicit-splits path with an equal send
        # vector so the backward can replay with the NEGOTIATED
        # received splits (parity: HorovodAlltoall's gradient uses
        # received_splits).  Replaying with equal splits instead would
        # crash — or silently misroute gradient rows — whenever ranks
        # contribute different dim-0 row counts (legal: the engine
        # only requires each rank's dim0 % size == 0).
        tensor = tf.convert_to_tensor(tensor)
        p = _participant_count(process_set)
        n = tensor.shape[0]
        if n is not None and int(n) % p:
            # the engine's error would blame a splits vector the user
            # never passed — raise the no-splits contract directly
            raise ValueError(
                f"alltoall dim0 {int(n)} not divisible by size {p}")
        dyn = tf.shape(tensor)[0]
        if n is None:
            # dynamic dim0 (tf.function with a [None] signature):
            # assert the contract at runtime so the failure names this
            # op, not a splits vector the user never passed
            tf.debugging.assert_equal(
                dyn % p, 0,
                message=f"alltoall dim0 not divisible by size {p}")
        eq = tf.fill([p], dyn // p)
        out, _received = alltoall(
            tensor, splits=eq, name=name, process_set=process_set)
        return out

    def _forward(x, s):
        if tf.executing_eagerly():
            o, rs = _hvt.alltoall(
                _to_engine(x), _np(s), process_set=process_set,
                name=name,
            )
            return (_from_engine(o, dtype=x.dtype),
                    tf.convert_to_tensor(
                        np.asarray(rs).astype(np.int32)))

        want_np = tensor.dtype.as_numpy_dtype

        def _pyfn(t, sp):
            o, rs = _hvt.alltoall(t.numpy(), sp.numpy(),
                                  process_set=process_set, name=name)
            o = np.asarray(o)
            # same Tout contract as _graph_op._np_out: restore the
            # declared dtype (float64 computes at f32 wire precision
            # with x64 off)
            if o.dtype != np.dtype(want_np):
                o = o.astype(want_np)
            return (tf.convert_to_tensor(o),
                    tf.convert_to_tensor(np.asarray(rs).astype(np.int32)))

        o, rs = tf.py_function(
            _pyfn, [x, s], Tout=[tensor.dtype, tf.int32],
        )
        o.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
        return o, rs

    # Parity: RegisterGradient('HorovodAlltoall') — route each gradient
    # chunk back to its sender by replaying the exchange with the
    # RECEIVED splits; the splits input itself gets no gradient.
    @tf.custom_gradient
    def _op(x, s):
        out, rsplits = _forward(x, s)

        def grad(dy, drsplits):
            g, _ = alltoall(dy, splits=rsplits,
                            process_set=process_set)
            return g, None

        return (out, rsplits), grad

    s = splits if tf.is_tensor(splits) else tf.convert_to_tensor(
        np.asarray(splits).astype(np.int32))
    return _op(tf.convert_to_tensor(tensor), s)


@_no_autograph
def reducescatter(tensor, op=None, name=None, process_set=None):
    def impl(x):
        return _hvt.reducescatter(
            x, op=op, process_set=process_set, name=name
        )

    shape = tf.TensorShape([None]).concatenate(tensor.shape[1:]) \
        if tensor.shape.rank is not None and tensor.shape.rank > 0 else None

    # Parity: RegisterGradient('HorovodReducescatter') — the adjoint of
    # reduce+scatter is gather(+identity): allgather the shard grads;
    # an Average forward additionally averages the backward.
    @tf.custom_gradient
    def _op(x):
        y = _graph_op(impl, [x], x.dtype, shape)

        def grad(dy):
            from ..comm.reduce_ops import ReduceOp, normalize_op

            rop = normalize_op(op, None)
            if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
                raise NotImplementedError(
                    f"gradient of a {rop.name} reducescatter is not "
                    "defined")
            g = allgather(dy, process_set=process_set)
            if rop == ReduceOp.AVERAGE:
                g = g / tf.cast(_participant_count(process_set),
                                g.dtype)
            return g

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


@_no_autograph
def barrier(process_set=None):
    _hvt.barrier(process_set=process_set)


@_no_autograph
def join(device=None) -> int:
    return _hvt.join(device)
