"""horovod_tpu.torch — source-compatible ``horovod.torch`` frontend.

Parity surface of horovod/torch/__init__.py: lifecycle, topology
queries, eager collectives on torch tensors (sync, async, in-place,
grouped), DistributedOptimizer, Compression, broadcast_parameters /
broadcast_optimizer_state / broadcast_object, SyncBatchNorm, join.

Usage (identical shape to the reference)::

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
"""

from __future__ import annotations

import horovod_tpu as _hvt

# lifecycle + topology (parity: HorovodBasics surface)
init = _hvt.init
shutdown = _hvt.shutdown
is_initialized = _hvt.is_initialized
rank = _hvt.rank
size = _hvt.size
local_rank = _hvt.local_rank
local_size = _hvt.local_size
cross_rank = _hvt.cross_rank
cross_size = _hvt.cross_size
is_homogeneous = _hvt.is_homogeneous
mpi_enabled = _hvt.mpi_enabled
mpi_built = _hvt.mpi_built
mpi_threads_supported = _hvt.mpi_threads_supported
gloo_enabled = _hvt.gloo_enabled
gloo_built = _hvt.gloo_built
nccl_built = _hvt.nccl_built
ddl_built = _hvt.ddl_built
ccl_built = _hvt.ccl_built
cuda_built = _hvt.cuda_built
rocm_built = _hvt.rocm_built
xla_built = _hvt.xla_built
start_timeline = _hvt.start_timeline
stop_timeline = _hvt.stop_timeline

ProcessSet = _hvt.ProcessSet
add_process_set = _hvt.add_process_set
remove_process_set = _hvt.remove_process_set
HorovodInternalError = _hvt.HorovodInternalError
HostsUpdatedInterrupt = _hvt.HostsUpdatedInterrupt

from .compression import Compression  # noqa: E402
from .mpi_ops import (  # noqa: E402
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    sparse_allreduce_async,
    synchronize,
)
from .functions import (  # noqa: E402
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from .optimizer import DistributedOptimizer  # noqa: E402
from .sync_batch_norm import SyncBatchNorm  # noqa: E402
from . import elastic  # noqa: E402  (hvd.elastic.TorchState parity)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size",
    "mpi_enabled", "mpi_built", "mpi_threads_supported", "gloo_enabled",
    "gloo_built", "nccl_built", "ddl_built", "ccl_built", "cuda_built",
    "rocm_built", "xla_built",
    "start_timeline", "stop_timeline",
    "ProcessSet", "add_process_set", "remove_process_set",
    "HorovodInternalError", "HostsUpdatedInterrupt",
    "Compression", "Sum", "Average", "Adasum", "Min", "Max", "Product",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_", "grouped_allreduce_async",
    "grouped_allgather", "grouped_allgather_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "barrier", "join",
    "sparse_allreduce_async", "synchronize", "poll",
    "broadcast_parameters", "broadcast_optimizer_state",
    "broadcast_object", "allgather_object",
    "DistributedOptimizer", "SyncBatchNorm",
]


def __getattr__(name: str):
    # forward the live module attribute (parity: per-frontend
    # hvd.global_process_set); AttributeError keeps hasattr contracts
    if name == "global_process_set":
        return getattr(_hvt, "global_process_set")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
