"""Eager-path micro-benchmark: allreduce GB/s vs tensor size, fused vs
unfused, through the torch frontend adapter (VERDICT round-1 task 5).

The reference measures its eager path with
examples/pytorch/pytorch_synthetic_benchmark.py; this is the
collective-level equivalent.  Runs single-process by default (adapter +
engine dispatch overheads dominate — the quantity of interest for the
zero-copy work); pass --np 2+ to run the same sweep across real worker
processes via the runner.

Prints one JSON line per configuration:
  {"bench": "eager_allreduce", "nbytes": ..., "mode": "sync|async_fused",
   "gbps": ..., "us_per_op": ...}
"""

import argparse
import json
import time

# Schema of the torch DistributedOptimizer end-to-end step-time row
# (enforced by tests/test_bench_guard.py so future rounds stay
# comparable): one row per run, produced by build_torch_step_row.
TORCH_STEP_KEYS = (
    "bench", "np", "param_tensors", "param_bytes", "ms_per_step",
    "steps_per_s",
)

# Schedule-prediction columns carried by every controller-driven row
# since round 7 (enforced by tests/test_bench_guard.py): the fraction
# of cycles in the timed window that skipped the KV round trip, and
# the mispredict count/rate — a steady-state row with prediction
# healthy shows predicted_fraction near 1 and zero mispredicts.
# Round 8 adds zero_copy_fraction: the share of fused-allreduce ops in
# the window that rode the enqueue-time-packed exchange buffer instead
# of the drain-time staged copy (None when the window fused nothing).
PREDICT_ROW_KEYS = ("predicted_fraction", "mispredicts",
                    "mispredict_rate", "zero_copy_fraction")


def snapshot_predict_counters():
    """Controller cycle/prediction/fusion-path counter values for THIS
    process (rank 0 when run under the runner: per_rank[0] is what
    lands in the report)."""
    from horovod_tpu.obs import metrics as obs_metrics

    return {
        "cycles": obs_metrics.counter(
            "hvtpu_controller_cycles_total").value(),
        "predicted": obs_metrics.counter(
            "hvtpu_controller_predicted_cycles_total").value(),
        "mispredicts": obs_metrics.counter(
            "hvtpu_controller_mispredicts_total").value(),
        "zero_copy": obs_metrics.counter(
            "hvtpu_fusion_zero_copy_ops_total").value(),
        "staged": obs_metrics.counter(
            "hvtpu_fusion_staged_copies_total").value(),
    }


def build_predict_stats(before, after):
    """The PREDICT_ROW_KEYS columns from two snapshot_predict_counters
    readings bracketing a timed window.  Fractions are None when the
    window ran no controller cycles (e.g. a 1-proc dispatch bench
    short-circuiting the wire).  The fusion-path keys default to 0 so
    older 3-key snapshots (and the schema test's fixtures) still
    build."""
    cycles = after["cycles"] - before["cycles"]
    predicted = after["predicted"] - before["predicted"]
    mis = after["mispredicts"] - before["mispredicts"]
    zc = after.get("zero_copy", 0) - before.get("zero_copy", 0)
    staged = after.get("staged", 0) - before.get("staged", 0)
    return {
        "predicted_fraction": (round(predicted / cycles, 3)
                               if cycles else None),
        "mispredicts": int(mis),
        "mispredict_rate": (round(mis / cycles, 4)
                            if cycles else None),
        "zero_copy_fraction": (round(zc / (zc + staged), 3)
                               if (zc + staged) else None),
    }


def build_torch_step_row(np_, param_tensors, param_bytes, ms_per_step):
    """One JSON row for the torch DistributedOptimizer step-time bench
    (bench == "eager_torch_step")."""
    return {
        "bench": "eager_torch_step",
        "np": int(np_),
        "param_tensors": int(param_tensors),
        "param_bytes": int(param_bytes),
        "ms_per_step": round(float(ms_per_step), 3),
        "steps_per_s": (round(1000.0 / ms_per_step, 3)
                        if ms_per_step > 0 else 0.0),
    }


def run_torch_step(sizes_mb, iters, warmup=3):
    """End-to-end torch ``DistributedOptimizer`` step time (the
    measurement VERDICT r5 notes never existed): forward + backward +
    per-parameter async allreduce through the eager controller +
    step(), on a model with the many-same-shape-buckets structure real
    training produces.  ``sizes_mb`` selects the total gradient
    payload; run with --np 4 for the headline row."""
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    results = []
    for mb in sizes_mb:
        # 8 equal square layers -> 16 parameter tensors (8 weights +
        # 8 biases): one async allreduce per tensor per step, the
        # optimizer bucket pattern the controller's steady-state
        # bypass + burst gate exist for.
        n_layers = 8
        dim = max(16, int((mb * (1 << 20) / 4 / n_layers) ** 0.5))
        torch.manual_seed(0)  # identical init on every rank
        model = torch.nn.Sequential(*[
            torch.nn.Linear(dim, dim) for _ in range(n_layers)
        ])
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=1e-3),
            named_parameters=model.named_parameters(),
        )
        loss_fn = torch.nn.MSELoss()
        x = torch.randn(32, dim)
        y = torch.randn(32, dim)

        def step():
            opt.zero_grad()
            loss_fn(model(x), y).backward()
            opt.step()

        for _ in range(warmup):
            step()
        snap = snapshot_predict_counters()
        t0 = time.perf_counter()
        for _ in range(iters):
            step()
        dt = (time.perf_counter() - t0) / iters
        params = list(model.parameters())
        row = build_torch_step_row(
            hvd.size(), len(params),
            sum(p.numel() * 4 for p in params), dt * 1e3,
        )
        row["dim"] = dim
        row.update(build_predict_stats(snap, snapshot_predict_counters()))
        results.append(row)
    return results


def run_sweep(sizes_mb, iters, warmup=3):
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd

    hvd.init()
    results = []
    for mb in sizes_mb:
        n = int(mb * (1 << 20) / 4)
        t = torch.ones(n, dtype=torch.float32)

        # sync path
        for _ in range(warmup):
            hvd.allreduce(t, op=hvd.Sum, name=f"warm.{n}")
        t0 = time.perf_counter()
        for i in range(iters):
            hvd.allreduce(t, op=hvd.Sum, name=f"sync.{n}")
        dt = (time.perf_counter() - t0) / iters
        results.append({
            "bench": "eager_allreduce", "nbytes": n * 4, "mode": "sync",
            "gbps": n * 4 / dt / 1e9, "us_per_op": dt * 1e6,
        })

        # async fused path: 8 tensors of n/8 through the controller
        k = 8
        chunk = torch.ones(max(n // k, 1), dtype=torch.float32)
        # warm up on the SAME names the timed loop uses: the row
        # measures the steady state, and since round 7 that includes
        # the predictor (first occurrence of a name set is observed,
        # not predicted — distinct warmup names would bill that
        # verification to the timed window)
        for _ in range(2 * warmup):
            hs = [hvd.allreduce_async(chunk, op=hvd.Sum,
                                      name=f"as.{n}.{i}")
                  for i in range(k)]
            for h in hs:
                hvd.synchronize(h)
        snap = snapshot_predict_counters()
        t0 = time.perf_counter()
        for it in range(iters):
            hs = [hvd.allreduce_async(chunk, op=hvd.Sum,
                                      name=f"as.{n}.{i}")
                  for i in range(k)]
            for h in hs:
                hvd.synchronize(h)
        dt = (time.perf_counter() - t0) / iters
        total = chunk.numel() * 4 * k
        results.append({
            "bench": "eager_allreduce", "nbytes": total,
            "mode": "async_fused", "gbps": total / dt / 1e9,
            "us_per_op": dt * 1e6 / k,
            **build_predict_stats(snap, snapshot_predict_counters()),
        })

        # pipelined async: iteration k+1's batch is enqueued BEFORE
        # iteration k's handles synchronize (depth-2 software
        # pipeline), so batch k+1's negotiation/KV exchange overlaps
        # batch k's data-plane execution on the controller's executor
        # thread — the overlap the async API exists for (a training
        # step's early grads negotiate while later layers' backward
        # still runs).  Two alternating name sets keep pending names
        # unique; both are steady-state cache hits after warmup.
        def batch(it):
            return [hvd.allreduce_async(chunk, op=hvd.Sum,
                                        name=f"ap.{n}.{it % 2}.{i}")
                    for i in range(k)]
        for it in range(2 * warmup):
            for h in batch(it):
                hvd.synchronize(h)
        snap = snapshot_predict_counters()
        t0 = time.perf_counter()
        prev = None
        for it in range(iters):
            hs = batch(it)
            if prev is not None:
                for h in prev:
                    hvd.synchronize(h)
            prev = hs
        for h in prev:
            hvd.synchronize(h)
        dt = (time.perf_counter() - t0) / iters
        results.append({
            "bench": "eager_allreduce", "nbytes": total,
            "mode": "async_fused_pipe", "gbps": total / dt / 1e9,
            "us_per_op": dt * 1e6 / k,
            **build_predict_stats(snap, snapshot_predict_counters()),
        })
    return results


def run_compression_ab(sizes_mb, iters, warmup=3):
    """Compression A/B on the sync eager wire (VERDICT round-3 task 5:
    make fp16's '~2x on comm-bound models' claim measurable).  Runs
    per-rank inside real worker processes (P>=2, CPU gloo — the wire
    is actual cross-process traffic); reports GB/s of PAYLOAD moved per
    compression mode, so the speedup column is the wire shrink made
    visible end-to-end (compress + smaller exchange + decompress)."""
    import numpy as np
    import jax.numpy as jnp

    import horovod_tpu as hvt
    from horovod_tpu.comm.compression import Compression

    hvt.init()
    modes = [("none", Compression.none), ("fp16", Compression.fp16),
             ("bf16", Compression.bf16), ("int8", Compression.int8)]
    results = []
    for mb in sizes_mb:
        n = int(mb * (1 << 20) / 4)
        x = jnp.ones((n,), jnp.float32)
        base = None
        for name, comp in modes:
            def op():
                return np.asarray(
                    hvt.allreduce(x, op=hvt.Sum, compression=comp,
                                  name=f"ab.{name}.{n}"))
            for _ in range(warmup):
                op()
            t0 = time.perf_counter()
            for _ in range(iters):
                op()
            dt = (time.perf_counter() - t0) / iters
            gbps = n * 4 / dt / 1e9
            if name == "none":
                base = gbps
            results.append({
                "bench": "eager_allreduce_compression",
                "nbytes": n * 4, "compression": name,
                "payload_gbps": round(gbps, 3),
                "us_per_op": round(dt * 1e6, 1),
                "speedup_vs_none": round(gbps / base, 3),
            })
    hvt.shutdown()
    return results


def run_tf_graph_sweep(sizes_mb, iters, warmup=3):
    """tf.py_function collective overhead (VERDICT round-2 task 6):
    the graph-mode TF frontend routes collectives through
    tf.py_function; this measures eager vs traced dispatch so the
    round-trip cost is a tracked number, not folklore."""
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    results = []
    for mb in sizes_mb:
        n = int(mb * (1 << 20) / 4)
        t = tf.ones((n,), tf.float32)

        for mode in ("eager", "graph"):
            if mode == "graph":
                @tf.function
                def red(x):
                    return hvd.allreduce(x, op=hvd.Sum)
                fn = red
            else:
                def fn(x):
                    return hvd.allreduce(x, op=hvd.Sum)
            for _ in range(warmup):
                fn(t)
            t0 = time.perf_counter()
            for _ in range(iters):
                fn(t)
            dt = (time.perf_counter() - t0) / iters
            results.append({
                "bench": "eager_allreduce_tf", "nbytes": n * 4,
                "mode": mode, "gbps": n * 4 / dt / 1e9,
                "us_per_op": dt * 1e6,
            })
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sizes-mb", default="0.25,1,4,16,64")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--np", type=int, default=1,
                   help="worker processes (1 = in-process)")
    p.add_argument("--cpu-devices", type=int, default=None)
    p.add_argument("--tf", action="store_true",
                   help="run the TF frontend sweep (eager vs "
                        "tf.function/py_function dispatch)")
    p.add_argument("--compression-ab", action="store_true",
                   help="A/B the sync wire across compression modes "
                        "(use with --np 4)")
    p.add_argument("--torch-step", action="store_true",
                   help="end-to-end torch DistributedOptimizer step "
                        "time (use with --np 4)")
    args = p.parse_args()
    sizes = [float(s) for s in args.sizes_mb.split(",")]

    sweep = (run_torch_step if args.torch_step
             else run_compression_ab if args.compression_ab
             else run_tf_graph_sweep if args.tf else run_sweep)
    if args.np == 1:
        if args.cpu_devices:
            from horovod_tpu.core.state import force_cpu_devices

            force_cpu_devices(args.cpu_devices)
        results = sweep(sizes, args.iters)
    else:
        from horovod_tpu.core import retry as core_retry
        from horovod_tpu.runner import run as hvt_run

        # np>1 on localhost occasionally trips the jaxlib/gloo CPU
        # teardown race (a rank SIGSEGVs; docs/robustness.md): retry
        # via the named policy, classifying the crash exit too.
        policy = core_retry.gloo_teardown_policy()
        per_rank = core_retry.call(
            core_retry.RetryPolicy(
                name=policy.name, max_attempts=policy.max_attempts,
                base_delay_s=policy.base_delay_s,
                retryable=lambda e: (core_retry.is_gloo_infra_error(str(e))
                                     or "-11" in str(e)),
            ),
            hvt_run, sweep,
            args=(sizes, args.iters), np=args.np,
            cpu_devices=args.cpu_devices or 1, timeout=1800.0,
        )
        results = per_rank[0]
        for r in results:
            r["np"] = args.np
    for r in results:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
