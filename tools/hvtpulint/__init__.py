"""hvtpulint — zero-dependency static analysis for the hvtpu tree.

Seven passes guard invariants that are otherwise only enforced at
runtime (see docs/static-analysis.md):

  wire-twin        C++ wire format (native/src) vs the Python twin
  rank-divergence  collectives issued under rank-dependent control flow
  thread-safety    guarded-by lock discipline in eager/controller.py
  knob-registry    HVTPU_* env knobs vs the generated docs/knobs.md
  metrics-catalog  registered metrics vs docs/observability.md vs bench
  sim-purity       no host time / ambient RNG in horovod_tpu/sim
  kv-discipline    raw coordination-client KV calls outside the
                   FencedKV/ResilientKV wrappers (core/retry.py)

Everything here is stdlib-only (ast + re); the C++ side is scanned
lexically, never compiled.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESSION_FILE = ".hvtpulint.suppress"

# Directories never scanned by the tree-walking passes.
SKIP_DIRS = {
    ".git", "__pycache__", "build", "dist", ".eggs", "node_modules",
    "lint_fixtures",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding.

    ``key`` is the stable suppression key: it must be whitespace-free
    and should survive unrelated edits (so suppressions key on
    pass/file/symbol rather than line numbers).
    """

    pass_name: str
    path: str  # repo-relative posix path ("-" for repo-level findings)
    line: int  # 1-based; 0 when the finding has no single line
    key: str
    message: str

    def format_text(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.pass_name}] {self.message} (key: {self.key})"

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


class Project:
    """Root-anchored file access with a shared AST cache.

    Passes receive a Project rather than raw paths so the tier-1
    clean-tree run parses each Python file at most once across all
    passes.
    """

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self._text: Dict[Path, Optional[str]] = {}
        self._ast: Dict[Path, Optional[ast.Module]] = {}
        self._errors: List[Finding] = []

    # -- file access -------------------------------------------------
    def rel(self, path: Path) -> str:
        try:
            return path.resolve().relative_to(self.root).as_posix()
        except ValueError:
            return path.as_posix()

    def read(self, path: Path) -> Optional[str]:
        path = Path(path)
        if not path.is_absolute():
            path = self.root / path
        if path not in self._text:
            try:
                self._text[path] = path.read_text(encoding="utf-8")
            except OSError:
                self._text[path] = None
        return self._text[path]

    def parse(self, path: Path) -> Optional[ast.Module]:
        path = Path(path)
        if not path.is_absolute():
            path = self.root / path
        if path not in self._ast:
            src = self.read(path)
            if src is None:
                self._ast[path] = None
            else:
                try:
                    self._ast[path] = ast.parse(src, filename=str(path))
                except SyntaxError as exc:
                    self._ast[path] = None
                    self._errors.append(Finding(
                        "parse", self.rel(path), exc.lineno or 0,
                        f"syntax-error:{path.name}",
                        f"could not parse: {exc.msg}"))
        return self._ast[path]

    def py_files(self, *rel_dirs: str) -> List[Path]:
        """All .py files under the given repo-relative dirs (sorted)."""
        out: List[Path] = []
        for rel in rel_dirs:
            base = self.root / rel
            if base.is_file() and base.suffix == ".py":
                out.append(base)
                continue
            if not base.is_dir():
                continue
            for p in sorted(base.rglob("*.py")):
                # Root-relative skip: a fixture tree rooted *inside* a
                # skipped dir (tests/lint_fixtures/<case>) still scans.
                try:
                    parts = p.relative_to(self.root).parts
                except ValueError:
                    parts = p.parts
                if any(part in SKIP_DIRS for part in parts):
                    continue
                out.append(p)
        return out

    def missing(self, pass_name: str, rel_path: str) -> Finding:
        """A required input file is gone — fail loudly instead of
        silently disabling the pass (guards against renames)."""
        return Finding(pass_name, rel_path, 0,
                       f"missing-file:{Path(rel_path).name}",
                       "required input file is missing or unreadable")

    @property
    def parse_errors(self) -> List[Finding]:
        return list(self._errors)


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Suppression:
    pass_name: str
    key: str
    justification: str
    line: int
    used: bool = False


def load_suppressions(path: Path) -> Tuple[List[Suppression], List[Finding]]:
    """Parse the suppression file.

    Format (one entry per line)::

        <pass-name> <key> <justification -- mandatory free text>

    Blank lines and ``#`` comments are ignored.  An entry without a
    justification is itself a finding: silencing a check must leave a
    written reason behind.
    """
    entries: List[Suppression] = []
    findings: List[Finding] = []
    if not path.is_file():
        return entries, findings
    rel = path.name
    for lineno, raw in enumerate(path.read_text(encoding="utf-8").splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split(None, 2)
        if len(parts) < 3 or not parts[2].strip():
            findings.append(Finding(
                "suppressions", rel, lineno, f"malformed:{lineno}",
                "suppression entry needs '<pass> <key> <justification>' "
                "with a non-empty justification"))
            continue
        entries.append(Suppression(parts[0], parts[1], parts[2].strip(), lineno))
    return entries, findings


def apply_suppressions(findings: Iterable[Finding],
                       entries: List[Suppression],
                       suppress_rel: str) -> List[Finding]:
    """Filter suppressed findings; flag unused suppression entries."""
    kept: List[Finding] = []
    for f in findings:
        hit = None
        for s in entries:
            if s.pass_name == f.pass_name and s.key == f.key:
                hit = s
                break
        if hit is not None:
            hit.used = True
        else:
            kept.append(f)
    for s in entries:
        if not s.used:
            kept.append(Finding(
                "suppressions", suppress_rel, s.line,
                f"unused:{s.pass_name}:{s.key}",
                f"suppression '{s.pass_name} {s.key}' matched nothing — "
                "delete it or fix the key"))
    return kept


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------

def _registry() -> Dict[str, Callable[[Project], List[Finding]]]:
    # Imported lazily so `import tools.hvtpulint` stays cheap and the
    # passes can import this module for Finding/Project.
    from . import (knob_registry, kv_discipline, metrics_catalog,
                   rank_divergence, sim_purity, thread_safety, wire_twin)
    return {
        "wire-twin": wire_twin.run,
        "rank-divergence": rank_divergence.run,
        "thread-safety": thread_safety.run,
        "knob-registry": knob_registry.run,
        "metrics-catalog": metrics_catalog.run,
        "sim-purity": sim_purity.run,
        "kv-discipline": kv_discipline.run,
    }


def pass_names() -> List[str]:
    return list(_registry())


def run_passes(root: Path,
               only: Optional[Sequence[str]] = None,
               suppress_path: Optional[Path] = None) -> List[Finding]:
    """Run the selected passes and return unsuppressed findings."""
    project = Project(root)
    registry = _registry()
    names = list(only) if only else list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown pass(es): {', '.join(unknown)}; "
                         f"available: {', '.join(registry)}")
    findings: List[Finding] = []
    for name in names:
        findings.extend(registry[name](project))
    findings.extend(project.parse_errors)

    if suppress_path is None:
        suppress_path = project.root / SUPPRESSION_FILE
    entries, bad = load_suppressions(suppress_path)
    if only:
        # A partial run must not report entries for passes it skipped.
        entries = [s for s in entries if s.pass_name in names]
    findings = apply_suppressions(findings, entries, suppress_path.name)
    findings.extend(bad)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_name, f.key))
    return findings
