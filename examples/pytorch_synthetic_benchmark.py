"""Torch-frontend synthetic benchmark — the horovod_tpu surface of the
reference's measurement tool (examples/pytorch/
pytorch_synthetic_benchmark.py, the script behind BASELINE.md's
published numbers): random data, timed training iterations, per-rank
and aggregate images/sec with the same log format.

Only the import line changes from the reference idiom
(``import horovod.torch as hvd`` -> ``import horovod_tpu.torch as
hvd``).  The default model is a small conv net so the *eager torch*
data path (DLPack adapter -> eager controller -> fused collectives) is
what's being measured — for peak TPU numbers use the jit-path
benchmark at the repo root (bench.py), which is the TPU-idiomatic
equivalent of this script.

Run:  hvtpurun -np 2 --cpu-devices 1 python \
          examples/pytorch_synthetic_benchmark.py --num-iters 3
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class SmallConvNet(nn.Module):
    """Stand-in for torchvision's resnet50 (unavailable offline): same
    training-loop shape, tractable on the CPU-backed torch eager path."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 16, 3, stride=2, padding=1)
        self.conv2 = nn.Conv2d(16, 32, 3, stride=2, padding=1)
        self.fc = nn.Linear(32 * 8 * 8, num_classes)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))
        return self.fc(x.flatten(1))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="compress gradients to fp16 on the wire")
    p.add_argument("--use-adasum", action="store_true",
                   help="Adasum reduction instead of averaging")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(1 + hvd.rank())

    model = SmallConvNet()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01)

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer,
        named_parameters=model.named_parameters(),
        compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 10, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        output = model(data)
        loss = F.cross_entropy(output, target)
        loss.backward()
        optimizer.step()

    def log(s):
        if hvd.rank() == 0:
            print(s, flush=True)

    log(f"Model: SmallConvNet, Batch size: {args.batch_size}, "
        f"number of ranks: {hvd.size()}")

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for x in range(args.num_iters):
        t = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        log(f"Iter #{x}: {img_sec:.1f} img/sec per rank")
        img_secs.append(img_sec)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    log(f"Img/sec per rank: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
    log(f"Total img/sec on {hvd.size()} rank(s): "
        f"{hvd.size() * img_sec_mean:.1f} "
        f"+-{hvd.size() * img_sec_conf:.1f}")


if __name__ == "__main__":
    main()
